"""Optimizer — the training runtime (BigDL optim/Optimizer.scala:42,
LocalOptimizer.scala:41, DistriOptimizer.scala:88-421).

TPU-first translation of the reference's two-level data parallelism:

- intra-node thread clones (DistriOptimizer.scala:116-118) -> the per-chip
  batch dimension; XLA vectorizes.
- AllReduceParameter's reduce-scatter/optimizer/all-gather over Spark
  BlockManager (AllReduceParameter.scala:214-303) -> ONE compiled step:
  forward + backward + gradient mean over the `data` mesh axis + optimizer
  update, jitted together so XLA fuses the collective into the backward pass
  and overlaps it with compute over ICI.
- The Spark driver loop (iteration barrier, triggers, metrics, checkpoint)
  -> this host Python loop.

The straggler-dropping machinery (DistriOptimizer.scala:337-365) has no TPU
equivalent — a synchronous pod has no stragglers — so ``set_drop_module_
property`` is accepted as a documented no-op for API parity. The
retry-from-checkpoint loop (DistriOptimizer.scala:789-855) IS kept.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu import faults
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.prefetch import batch_signature, stack_minibatches
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.nn.module import AUX_LOSS_KEY, Criterion, Module
from bigdl_tpu.optim.optim_method import OptimMethod, SGD
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import ValidationMethod
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random import RandomGenerator

logger = logging.getLogger("bigdl_tpu")

# process-wide training throughput counters (telemetry registry; the
# per-run phase times ride the Metrics histograms below)
_STEP_COUNT = telemetry.counter("train/optimizer/steps",
                                "optimizer steps completed")
_RECORD_COUNT = telemetry.counter("train/optimizer/records",
                                  "training records processed")
_RECOVERIES = telemetry.counter(
    "train/optimizer/recoveries",
    "retry-from-checkpoint recoveries performed by optimize()")
# mixed-precision observability (Optimizer.set_precision): the loss
# scale and cumulative skipped steps are read off the (already-fetched)
# scaler state once per host sync; the policy/bytes gauges are set once
# at state layout
_LOSS_SCALE = telemetry.gauge(
    "train/precision/loss_scale",
    "current dynamic loss scale (1.0 when the policy does not scale)")
_SKIPPED_STEPS = telemetry.gauge(
    "train/precision/skipped_steps",
    "cumulative optimizer steps skipped on non-finite gradients")
_POLICY_INFO = telemetry.gauge(
    "train/precision/policy_info",
    "active precision policy (labels carry the dtypes); value is 1")
_PARAMS_F32_BYTES = telemetry.gauge(
    "train/precision/params_f32_bytes_per_chip",
    "per-chip param bytes the same layout would cost at float32 — the "
    "'before' against train/memory/params_bytes_per_chip")
_OPT_F32_BYTES = telemetry.gauge(
    "train/precision/opt_state_f32_bytes_per_chip",
    "per-chip optimizer-state bytes at float32 — the 'before' against "
    "train/memory/opt_state_bytes_per_chip")


class Metrics:
    """Named counters (optim/Metrics.scala:31) — host dict, no Spark
    accumulators needed.

    Migrated onto the telemetry registry: every ``add`` also lands in a
    ``train/optimizer/<metric>`` histogram, so the TensorBoard /
    Prometheus / JSONL exporters and ``tools.diagnose`` see the SAME
    numbers ``summary()`` prints. The local per-run list (and the
    ``summary()`` format) are unchanged — this class stays the per-run
    view, the registry the process-wide one."""

    def __init__(self, registry=None):
        self.values: Dict[str, List[float]] = {}
        self._registry = registry if registry is not None \
            else telemetry.registry()
        self._instruments: Dict[str, Any] = {}

    @staticmethod
    def _slug(name: str) -> str:
        """'data time' -> 'data_time' (the family/component/metric
        charset the telemetry-audit gate enforces)."""
        import re
        return re.sub(r"[^a-z0-9_]+", "_", name.lower()).strip("_")

    def add(self, name: str, value: float):
        self.values.setdefault(name, []).append(value)
        h = self._instruments.get(name)
        if h is None:
            h = self._registry.histogram(
                f"train/optimizer/{self._slug(name)}",
                f"Optimizer Metrics series {name!r} (seconds)")
            self._instruments[name] = h
        h.observe(value)

    def summary(self) -> str:
        parts = []
        for k, v in self.values.items():
            parts.append(f"{k}: avg {np.mean(v):.4f}s over {len(v)}")
        return "; ".join(parts)


def _collect_aux_losses(state_tree):
    """Sum every reserved ``AUX_LOSS_KEY`` leaf in a model-state tree (MoE
    load-balance terms, nn/moe.py). Only the dunder-namespaced key joins
    the objective — a user state entry named "aux_loss" does not.
    Differentiable — called inside loss_fn."""
    total = 0.0
    flat, _ = jax.tree_util.tree_flatten_with_path(state_tree)
    for path, leaf in flat:
        keys = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
        if keys and keys[-1] == AUX_LOSS_KEY:
            total = total + leaf
    return total


def _fetch_replicated(x) -> np.ndarray:
    """Host-fetch a fully replicated device value, multi-host safe: a
    replicated array spanning non-addressable devices is not plain-
    readable, but any addressable shard holds the complete value."""
    try:
        return np.asarray(x)
    except Exception:
        return np.asarray(jax.device_get(x.addressable_shards[0].data))


def _to_scalar(x) -> float:
    """float(loss) that also works on multi-host global arrays."""
    return float(_fetch_replicated(x))


def _losses_list(losses, k: int):
    """The length-k loss vector a fused window returns, as host floats —
    ONE fetch per window."""
    return [float(v) for v in _fetch_replicated(losses).reshape(-1)[:k]]


def _record_scaler_gauges(opt_state):
    """Refresh the loss-scale/skipped-steps gauges from the (already
    synchronized) scaler state riding the optimizer-state tree — one
    cheap host read per sync, no extra device fetch ordering."""
    from bigdl_tpu.precision import SCALER_KEY
    ss = opt_state.get(SCALER_KEY) if isinstance(opt_state, dict) else None
    if ss is None:
        return
    _LOSS_SCALE.set(float(_fetch_replicated(ss["scale"])))
    _SKIPPED_STEPS.set(float(_fetch_replicated(ss["skipped"])))


def _window_stackable(batch: MiniBatch) -> bool:
    """True when every leaf of the MiniBatch is a dense HOST array —
    the only thing ``np.stack`` window stacking supports. Sparse COO
    batches keep the per-step path, and so do device-resident leaves
    (e.g. a ``device_prefetch``-staged pipeline): host-stacking those
    would silently round-trip device->host->device with a blocking
    sync per batch — the inverse of what windowing buys."""
    from bigdl_tpu.dataset.sample import HostBatchedCOO, SparseFeature

    def ok(x):
        if x is None:
            return True
        if isinstance(x, (list, tuple)):
            return all(ok(e) for e in x)
        return not isinstance(x, (HostBatchedCOO, SparseFeature,
                                  jax.Array))
    return ok(batch.input) and ok(batch.target)


def _allreduce_result(r):
    """Sum a ValidationResult across processes: gather (numerator,
    count) and rebuild, so every host reports the GLOBAL score."""
    from jax.experimental import multihost_utils

    from bigdl_tpu.optim.validation import AccuracyResult, LossResult

    value, count = r.result()
    arr = multihost_utils.process_allgather(
        np.array([value * count, count], np.float64))
    num, cnt = np.asarray(arr).reshape(-1, 2).sum(0)
    if isinstance(r, AccuracyResult):
        return AccuracyResult(int(round(num)), int(cnt))
    if isinstance(r, LossResult):
        return LossResult(float(num), int(cnt))
    return r  # unknown result type: keep the local value


def _local_rows(x) -> np.ndarray:
    """Materialize a (possibly multi-host, batch-sharded) array's rows
    held by THIS process, in batch order; plain arrays pass through."""
    try:
        return np.asarray(x)
    except Exception:
        shards = sorted(x.addressable_shards,
                        key=lambda s: (s.index[0].start or 0))
        seen, parts = set(), []
        for s in shards:  # dedupe replicated copies across local devices
            key = tuple((sl.start, sl.stop) for sl in s.index)
            if key in seen:
                continue
            seen.add(key)
            # scoring-path row materialization, one shard per local
            # device (bounded, not the checkpoint sweep)
            parts.append(np.asarray(jax.device_get(s.data)))  # bigdl: disable=blocking-copy-in-checkpoint
        return np.concatenate(parts)


def train_program_name(module: Module, suffix: str = "step") -> str:
    """The program-profile name a module's compiled train/eval/window
    program registers under (``telemetry.programs``) — ONE naming rule
    so the build sites and the rate-recording sync points agree. Uses
    the module's explicit ``set_name`` when given (stable across
    processes), else its class name."""
    name = getattr(module, "_name", None) or type(module).__name__
    return f"train/{name}/{suffix}"


def _batch_rows(inputs) -> int:
    """Leading-dim row count of a step's inputs (first leaf of a
    Table/list input) — the item basis program-profile MFU uses."""
    leaves = jax.tree_util.tree_leaves(inputs)
    return int(leaves[0].shape[0]) if leaves else 1


def build_train_step(module: Module, criterion: Criterion,
                     optim_method: OptimMethod,
                     aux_loss_weight: float = 0.01,
                     gradient_clip=None, zero=None, mesh=None,
                     sharding_rules=None, precision=None,
                     loss_scaler=None, seq_parallel=None):
    """The compiled hot path: loss + grad + update in one jit.

    Gradient normalization matches the reference (grads averaged over the
    global batch, DistriOptimizer.scala:296-310 divides by numFinished);
    param_scales implements layer-wise scaling / freeze. Auxiliary losses
    the model emits through its state (MoE load balancing) join the
    objective with weight ``aux_loss_weight`` so they actually produce
    router gradients. ``gradient_clip`` = ("constant", min, max) or
    ("l2norm", max_norm) applies the reference's gradient clipping
    (Optimizer.scala setConstantGradientClipping /
    setGradientClippingByl2Norm) to the aggregated gradients before the
    update — the global-L2 form is what keeps edge-of-stability recipes
    (classic PTB LSTM at lr 1.0) convergent.

    ``zero`` (a ``parallel.zero.ZeroConfig`` with ``mesh``, and the
    TP ``sharding_rules`` when params are rule-sharded) turns the
    update into its weight-update-sharded form: stage >= 2 constrains
    the fresh gradients to the 1/n data-axis layout (XLA lowers the
    gradient all-reduce to a reduce-scatter), the optimizer math then
    runs on shards, and the new params are constrained back to the
    at-rest layout — replicated/TP for stage <= 2 (the single
    all-gather), still sharded for stage 3 (forward/backward gather
    each layer just in time). Every new optimizer-state leaf is pinned
    to an explicit sharding so donated-jit out-shardings can never
    silently re-replicate a shard after the first update.

    ``precision`` (a ``precision.PrecisionPolicy``; None reads the
    legacy ``Engine`` dtype knobs) compiles the mixed-precision casts
    into the step: params/inputs cast to ``compute_dtype`` on entry,
    gradients come back in compute dtype (so a ZeRO reduce-scatter
    moves low-precision bytes), are cast to ``accum_dtype`` (f32) and
    unscaled, and the update runs on the f32 weights — the params tree
    itself when ``param_dtype`` is f32, else the f32 MASTER COPY kept
    in the optimizer state under ``precision.MASTER_KEY``. With
    ``loss_scaler`` (auto-created for f16 policies) the loss is scaled
    before ``jax.grad`` and a step with non-finite gradients is
    SKIPPED: params/optimizer state keep their previous values and the
    scaler backs off — all inside the compiled step, so the state
    machine rides the windowed scan carry bit-consistently.

    ``seq_parallel`` (a ``parallel.sequence.SeqParallelConfig``)
    installs sequence parallelism as a TRAIN-STEP policy: the model
    apply is traced under ``use_sequence_parallel``, so every
    ``MultiHeadAttention`` without an explicit ``ring_axis`` runs the
    ring/Ulysses kernel over the config's mesh axis. Like ``zero``,
    the policy no-ops quietly (dense attention, degree gauge reads 1)
    when it cannot apply — no shard_map in this jax build, no mesh, or
    the axis missing/size-1. The SP collectives trace INSIDE the step,
    so under ``set_steps_per_sync(K)`` they land inside the scan body
    and the windowed dispatch boundary stays collective-free; ZeRO
    composes orthogonally (weights shard over the data axis, attention
    activations over the sequence axis).
    """
    if gradient_clip is not None and gradient_clip[0] not in (
            "constant", "l2norm"):
        raise ValueError(
            f"gradient_clip kind must be 'constant' or 'l2norm', got "
            f"{gradient_clip[0]!r}")
    zero_active = zero is not None and zero.active_on(mesh)
    import contextlib
    sp_scope = contextlib.nullcontext
    if seq_parallel is not None:
        from bigdl_tpu.parallel.sequence import (record_degree,
                                                 use_sequence_parallel)
        if seq_parallel.active_on(mesh):
            sp_scope = lambda: use_sequence_parallel(seq_parallel)
            record_degree(seq_parallel.degree())
        else:
            record_degree(1)
    from bigdl_tpu.precision import (MASTER_KEY, SCALER_KEY,
                                     DynamicLossScaler, PrecisionPolicy)
    policy = precision if precision is not None \
        else PrecisionPolicy.from_engine()
    scaler = None
    if policy.needs_loss_scaling:
        scaler = loss_scaler if loss_scaler is not None \
            else DynamicLossScaler()

    def step(params, opt_state, model_state, rng, lr, inputs, targets):
        scaler_state = opt_state.get(SCALER_KEY) \
            if isinstance(opt_state, dict) else None
        master = opt_state.get(MASTER_KEY) \
            if isinstance(opt_state, dict) else None
        inner_opt = {k: v for k, v in opt_state.items()
                     if k not in (SCALER_KEY, MASTER_KEY)} \
            if isinstance(opt_state, dict) else opt_state
        if scaler is not None and scaler_state is None:
            raise ValueError(
                "loss-scaling policy needs the scaler state in "
                "opt_state[precision.SCALER_KEY]; seed it with "
                "scaler.init_state() (Optimizer.set_precision does "
                "this automatically)")
        if policy.needs_master and master is None:
            raise ValueError(
                "low-precision param_dtype needs the f32 master copy "
                "in opt_state[precision.MASTER_KEY] "
                "(Optimizer.set_precision seeds it automatically)")

        def loss_fn(p_c):
            # cast-on-entry at the step boundary: fwd/bwd run in
            # compute_dtype (bf16 on TPU — the analogue of the
            # reference's fp16 gradient compression,
            # FP16CompressedTensor.scala); norm stats/softmax/loss stay
            # f32 inside the layers; cast-on-exit hands the loss an
            # output_dtype (f32) tensor.
            x_c = policy.cast_to_compute(inputs)
            # the SP policy is installed for the TRACE of the apply —
            # attention modules adopt it; once compiled, the routing is
            # baked in (toggling later never mutates this program)
            with sp_scope():
                out, new_mstate = module.apply(p_c, model_state, x_c,
                                               training=True, rng=rng)
            out = policy.cast_output(out)
            loss = criterion.apply(out, targets)
            reg = module.regularization_loss(p_c)
            aux = _collect_aux_losses(new_mstate)
            total = loss + reg + aux_loss_weight * aux
            if scaler is not None:
                total = scaler.scale_loss(total, scaler_state)
            return total, (new_mstate, loss)

        # grads are taken wrt the COMPUTE-dtype params, so they arrive
        # in compute dtype — under ZeRO >= 2 the reduce-scatter below
        # therefore moves bf16/f16 bytes, half the f32 wire traffic
        p_c = policy.cast_to_compute(params)
        grads, (new_mstate, data_loss) = jax.grad(
            loss_fn, has_aux=True)(p_c)
        if zero_active and zero.stage >= 2:
            # the reduce-scatter point (arXiv:2004.13336): constrained
            # HERE, everything downstream — scaling, clipping, the
            # optimizer math — runs on 1/n shards
            from bigdl_tpu.parallel.zero import constrain_zero
            grads = constrain_zero(grads, mesh, zero, sharding_rules)
        grads = policy.cast_to_accum(grads)
        finite = None
        if scaler is not None:
            grads = scaler.unscale(grads, scaler_state)
            # the skip-step probe: checked AFTER unscaling so an
            # overflowed-scale inf is caught even when the raw f16
            # grads were finite
            finite = scaler.all_finite(grads)
        scales = module.param_scales(params)
        if any(s != 1.0 for s in jax.tree.leaves(scales)):
            grads = jax.tree.map(lambda g, s: g * s, grads, scales)
        if gradient_clip is not None:
            if gradient_clip[0] == "constant":
                lo, hi = gradient_clip[1], gradient_clip[2]
                grads = jax.tree.map(lambda g: jnp.clip(g, lo, hi),
                                     grads)
            else:  # global L2 norm accumulates f32 (sanctioned island)
                nrm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))  # bigdl: disable=implicit-upcast-in-trace
                    for g in jax.tree.leaves(grads)))
                scale = jnp.minimum(
                    1.0, gradient_clip[1] / jnp.maximum(nrm, 1e-12))
                grads = jax.tree.map(
                    lambda g: g * scale.astype(g.dtype), grads)
        # master-copy update: the f32 weights are the params tree when
        # param_dtype is f32, else the MASTER_KEY copy; low-precision
        # at-rest params are the master cast down after the update
        update_base = master if master is not None else params
        if master is None and policy.param_dtype != policy.accum_dtype:
            # no-master low-precision policy (the legacy Engine
            # default-dtype path): the update runs in param dtype,
            # exactly the pre-policy program
            from bigdl_tpu.precision import cast_floating
            grads = cast_floating(grads, policy.param_dtype)
        new_base, new_inner = optim_method.update(grads, inner_opt,
                                                  update_base, lr)
        if master is not None:
            new_master = new_base
            new_params = policy.cast_to_param(new_master)
        else:
            new_master = None
            new_params = new_base
        if finite is not None:
            # skip-step select: a non-finite gradient leaves params,
            # master and EVERY optimizer buffer (moments, Adam's t) at
            # their previous values; only the scaler state advances
            def keep_old(new, old):
                return jax.tree.map(
                    lambda n, o: jnp.where(finite, n, o), new, old)
            new_params = keep_old(new_params, params)
            new_inner = keep_old(new_inner, inner_opt)
            if new_master is not None:
                new_master = keep_old(new_master, master)
        new_opt = dict(new_inner) if isinstance(new_inner, dict) \
            else new_inner
        if new_master is not None:
            new_opt[MASTER_KEY] = new_master
        if scaler is not None:
            new_opt[SCALER_KEY] = scaler.next_state(scaler_state, finite)
        if zero_active:
            from bigdl_tpu.parallel.zero import (constrain_base,
                                                 constrain_zero)
            # pin EVERY fresh opt-state leaf (moments AND step
            # counters — and the f32 master copy, which shards exactly
            # like the optimizer state it lives in) to its explicit
            # sharded layout
            new_opt = constrain_zero(new_opt, mesh, zero, sharding_rules)
            if zero.stage == 3:
                # params stay sharded at rest; each layer all-gathers
                # just-in-time at its use inside the next fwd/bwd
                new_params = constrain_zero(new_params, mesh, zero,
                                            sharding_rules)
            else:
                # THE one params all-gather of the classic partitioned
                # parameter server (AllReduceParameter.scala:214-303)
                new_params = constrain_base(new_params, mesh,
                                            sharding_rules)
        return new_params, new_opt, new_mstate, data_loss

    jitted = jax.jit(step, donate_argnums=(0, 1, 2))
    # program-profile hook (telemetry.programs; one flag check when
    # profiling is off): the standalone step registers its XLA
    # cost/memory analysis under train/program/* on first execution
    return telemetry.programs.maybe_wrap_jitted(
        train_program_name(module), "train", jitted,
        donation="params,opt_state,model_state",
        items_for=lambda args, kwargs: _batch_rows(args[5]))


def build_eval_step(module: Module, out_sharding=None, precision=None):
    """``out_sharding`` pins the output layout (batch-sharded over the
    data axis on a mesh): GSPMD is otherwise free to replicate the
    output, and multi-host scoring slices each process's LOCAL rows —
    those must be the rows that process fed. ``precision`` (a non-noop
    ``PrecisionPolicy``) runs the forward in compute dtype with the
    output cast back — validation scores the precision that actually
    trains/serves."""
    if precision is not None and not precision.is_noop:
        def eval_step(params, model_state, inputs):
            out, _ = precision.apply_module(module, params, model_state,
                                            inputs, training=False)
            return out
    else:
        def eval_step(params, model_state, inputs):
            out, _ = module.apply(params, model_state, inputs,
                                  training=False)
            return out

    return telemetry.programs.maybe_wrap_jitted(
        train_program_name(module, "eval"), "train",
        jax.jit(eval_step, out_shardings=out_sharding),
        items_for=lambda args, kwargs: _batch_rows(args[2]))


def make_host_window(step):
    """The K-step fused host-feed window over ``step`` — ONE
    ``lax.scan`` dispatch per window, exactly the program
    ``set_steps_per_sync`` compiles: ``(params, opt_state, model_state,
    keys[K,...], lrs[K], xs[K,B,...], ys[K,B,...]) -> (params,
    opt_state, model_state, losses[K])`` with the carry donated.

    Factored out of the driver loop so the static program verifier
    (``analysis.programs``) lowers the very artifact the Optimizer
    dispatches — the windowed-HLO contracts (zero entry collectives,
    donation aliased through the scan carry) are checked on the real
    program, not a test replica."""
    def _window_host(p, o, m, keys, lrs, xs, ys):
        # scan over the [K, B, ...] stacked device buffer
        # (dataset.prefetch.stack_windows layout)
        def body(carry, sl):
            p, o, m = carry
            key, lr, x, yb = sl
            p, o, m, loss = step(p, o, m, key, lr, x, yb)
            return (p, o, m), loss
        (p, o, m), losses = jax.lax.scan(
            body, (p, o, m), (keys, lrs, xs, ys))
        return p, o, m, losses

    return jax.jit(_window_host, donate_argnums=(0, 1, 2))


class Optimizer:
    """Driver loop + fluent config surface (optim/Optimizer.scala:42).

    One class covers the reference's LocalOptimizer (single chip) and
    DistriOptimizer (multi-chip): the difference is only the mesh the batch
    is laid out over.
    """

    def __init__(self, model: Module, dataset: AbstractDataSet,
                 criterion: Criterion, batch_size: int = 32,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 data_axis: str = "data",
                 sharding_rules=None, zero1: bool = False):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.batch_size = batch_size
        self.mesh = mesh
        self.data_axis = data_axis
        # tensor/expert-parallel param layout (parallel/tp.py rules);
        # None = fully replicated params (pure DP, the reference's layout)
        self.sharding_rules = sharding_rules
        # ZeRO-1: optimizer state sharded over the data axis — the direct
        # analogue of the reference's per-node OWNED weight shard running
        # the OptimMethod (AllReduceParameter.scala:214-303). The bool is
        # the original knob; stages 2/3 (gradient reduce-scatter /
        # params-sharded-at-rest) arrive through set_zero(ZeroConfig).
        self.zero1 = zero1
        self.zero_config = None
        if zero1:
            from bigdl_tpu.parallel.zero import ZeroConfig
            self.zero_config = ZeroConfig(stage=1, data_axis=data_axis)
        self.optim_method: OptimMethod = SGD()
        self.end_when: Trigger = None
        # validation
        self.validation_trigger: Optional[Trigger] = None
        self.validation_dataset: Optional[AbstractDataSet] = None
        self.validation_methods: Optional[List[ValidationMethod]] = None
        # checkpoint
        self.checkpoint_trigger: Optional[Trigger] = None
        self.checkpoint_path: Optional[str] = None
        self.is_overwrite = False
        # elastic checkpointing (set_checkpoint keep_last/async_write):
        # retention depth, per-shard async writer, and the SIGTERM
        # grace handler (set_preemption_handler / BIGDL_PREEMPT_GRACE)
        self.checkpoint_keep_last: Optional[int] = None
        self.checkpoint_async = False
        self._ckpt_writer = None
        self._preempt_grace = False
        self._grace = None
        # summaries
        self.train_summary = None
        self.validation_summary = None
        # failure retry (DistriOptimizer.scala:789-855)
        # multi-host fixed-batch guard, tracked PER STREAM: validation may
        # legitimately use a different batch size than training
        self._mp_batch_rows: Dict[str, int] = {}
        self._stream = "train"
        self.retry_times = int(os.environ.get("BIGDL_FAILURE_RETRY_TIMES", 5))
        # base of the exponential backoff between retries: the first
        # retry sleeps equal-jittered [base/2, base), doubling per
        # attempt; BIGDL_FAILURE_RETRY_MAX_INTERVAL caps growth
        self.retry_interval_s = float(
            os.environ.get("BIGDL_FAILURE_RETRY_INTERVAL", 1.0))
        self.retry_max_interval_s = float(
            os.environ.get("BIGDL_FAILURE_RETRY_MAX_INTERVAL", 30.0))
        self.metrics = Metrics()
        # windowed step driver (set_steps_per_sync): K train steps fused
        # into one lax.scan dispatch, host syncs only at window
        # boundaries. 1 = the classic per-step loop.
        self.steps_per_sync = 1
        # mixed-precision policy (set_precision); None = the legacy
        # Engine dtype knobs (f32 unless configured)
        self._precision = None
        self._loss_scaler = None
        # sequence-parallel training policy (set_sequence_parallel);
        # None = dense attention
        self._seq_parallel = None
        # gradient clipping (Optimizer.scala setConstantGradientClipping
        # / setGradientClippingByl2Norm); None = off
        self._gradient_clip = None
        # opt-in pre-flight shape check (analysis/shapecheck.py); None =
        # off. Set via set_preflight_spec.
        self._preflight_spec = None
        # single-slot (dataset, jitted fn) cache for device-cached
        # validation — replacing the validation dataset must free the
        # old split's HBM-resident arrays, not pin them forever
        self._dc_eval: Optional[tuple] = None
        self.driver_state: Dict[str, Any] = {"epoch": 1, "neval": 1,
                                             "recordsProcessedThisEpoch": 0}
        self._drop_percentage = 0.0  # accepted, no-op on TPU

    # -- fluent config (Optimizer.scala:120-343) ---------------------------
    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_method = method
        return self

    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_validation(self, trigger: Trigger, dataset: AbstractDataSet,
                       methods: Sequence[ValidationMethod],
                       batch_size: Optional[int] = None) -> "Optimizer":
        # a DeviceCachedArrayDataSet bakes its batch size into the
        # compiled sample+forward — a conflicting request would be
        # silently dropped, so reject it up front, BEFORE any state
        # mutation (a caller catching the error keeps its old config)
        ds_bs = getattr(dataset, "batch_size", None)
        if batch_size is not None and ds_bs is not None \
                and hasattr(dataset, "eval_batch_fn_on") \
                and batch_size != ds_bs:
            raise ValueError(
                f"device-cached validation runs at the dataset's own "
                f"batch_size={ds_bs}; got conflicting batch_size="
                f"{batch_size} (omit it or rebuild the dataset)")
        self.validation_trigger = trigger
        self.validation_dataset = dataset
        self.validation_methods = list(methods)
        self._val_batch_size = batch_size or self.batch_size
        self._dc_eval = None  # new dataset: drop the old compiled slot
        return self

    def set_checkpoint(self, path: str, trigger: Trigger, *,
                       keep_last: Optional[int] = None,
                       async_write: bool = False) -> "Optimizer":
        """Checkpoint into ``path`` whenever ``trigger`` fires
        (Optimizer.scala:207 setCheckpoint), with the elastic
        extensions:

        ``async_write=True`` switches to the per-shard format-3 writer
        (``bigdl_tpu.elastic``): the step-loop stall shrinks to the
        device->host snapshot copy and the serialize/hash/commit tail
        runs on a background thread behind a barriered two-phase
        MANIFEST — a not-yet-committed checkpoint is never visible to
        ``find_latest_checkpoint``. Local/POSIX paths only.

        ``keep_last=N`` prunes older COMMITTED checkpoints beyond the
        newest N after each save — never the newest, never a
        ``*.corrupt-*`` quarantine, and safe concurrently with an
        in-flight async write."""
        from bigdl_tpu.utils import file_io
        if async_write and file_io.is_remote(path):
            raise ValueError(
                "async_write stages + renames on a local filesystem; "
                "remote checkpoint paths keep the sync format-2 writer")
        if keep_last is not None and int(keep_last) < 1:
            raise ValueError(
                f"keep_last must be >= 1, got {keep_last} (the newest "
                "committed checkpoint is never deleted)")
        if keep_last is not None and file_io.is_remote(path):
            raise ValueError(
                "keep_last retention walks + deletes local checkpoint "
                "dirs; on a remote store it would silently do nothing "
                "— manage object-store lifecycle rules instead")
        file_io.makedirs(path)
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        self.checkpoint_keep_last = None if keep_last is None \
            else int(keep_last)
        self.checkpoint_async = bool(async_write)
        if async_write and self._ckpt_writer is None:
            from bigdl_tpu.elastic import AsyncCheckpointWriter
            self._ckpt_writer = AsyncCheckpointWriter()
        return self

    def set_preemption_handler(self, enabled: bool = True) -> "Optimizer":
        """SIGTERM grace (``bigdl_tpu.elastic.preempt``): when the pod
        scheduler SIGTERMs this process, the step loop drains at the
        next boundary — flushes any in-flight async write, saves an
        EMERGENCY checkpoint synchronously, dumps a flight-recorder
        bundle — and exits through ``elastic.Preempted`` so the gang
        launcher relaunches (possibly at a different world size) and
        resumes from it. Also enabled by ``BIGDL_PREEMPT_GRACE=1``."""
        self._preempt_grace = bool(enabled)
        return self

    def overwrite_checkpoint(self) -> "Optimizer":
        self.is_overwrite = True
        return self

    def set_train_summary(self, summary) -> "Optimizer":
        self.train_summary = summary
        return self

    def set_val_summary(self, summary) -> "Optimizer":
        self.validation_summary = summary
        return self

    def set_model(self, new_model: Module) -> "Optimizer":
        """Swap the model before optimize() (Optimizer.scala:230)."""
        self.model = new_model
        # the device-cached validation slot closed over the OLD model's
        # forward at trace time — drop it or validation would silently
        # score the previous architecture
        self._dc_eval = None
        return self

    def set_state(self, state: Dict[str, Any]) -> "Optimizer":
        """Seed the driver's optimization state — epoch/neval counters
        etc. (Optimizer.scala:240 setState). Counter keys also reach
        the OptimMethod's state so epoch/iteration-driven lr schedules
        start from the seeded position, not epoch 1."""
        self.driver_state.update(dict(state))
        for k in ("epoch", "neval"):
            if k in state:
                self.optim_method.state[k] = state[k]
        return self

    def set_constant_gradient_clipping(self, min_value: float,
                                       max_value: float) -> "Optimizer":
        """Clip every gradient element into [min, max]
        (Optimizer.scala setConstantGradientClipping)."""
        if float(min_value) > float(max_value):
            raise ValueError(
                f"constant gradient clipping needs min <= max, got "
                f"[{min_value}, {max_value}] (jnp.clip would silently "
                "collapse every gradient to max)")
        self._gradient_clip = ("constant", float(min_value),
                               float(max_value))
        return self

    def set_gradient_clipping_by_l2_norm(self,
                                         clip_norm: float) -> "Optimizer":
        """Scale the aggregated gradients so their GLOBAL L2 norm never
        exceeds ``clip_norm`` (Optimizer.scala
        setGradientClippingByl2Norm) — the classic stabilizer for RNN
        recipes at aggressive learning rates."""
        self._gradient_clip = ("l2norm", float(clip_norm))
        return self

    def disable_gradient_clipping(self) -> "Optimizer":
        """Optimizer.scala disableGradientClipping."""
        self._gradient_clip = None
        return self

    def set_steps_per_sync(self, k: int) -> "Optimizer":
        """Fuse up to ``k`` train steps into ONE compiled ``lax.scan``
        program and sync the host only at window boundaries.

        The per-step loop round-trips to the host every iteration
        (fetch the loss, run trigger/metric bookkeeping, dispatch the
        next step), so the device idles in the gaps; with ``k > 1`` the
        whole window runs as one donated jitted dispatch and losses
        come back as a length-``k`` vector fetched once. Driver
        counters (``neval``, ``recordsProcessedThisEpoch``), triggers
        and summaries then REPLAY the ``k`` per-step increments
        host-side after the fetch, so observable semantics match the
        per-step loop; windows flush early at validation / checkpoint /
        end-trigger boundaries, epoch rollovers and shard rotations,
        and the driver falls back to ``k=1`` whenever a trigger depends
        on runtime values (``Loss``/``score``), a trigger's
        dependencies are unknown, or the LR schedule is metric-driven
        (Plateau) — see ``docs/performance.md``. ``Metrics``/telemetry
        are recorded once per window with amortized ``t_data`` /
        ``t_compute`` attribution."""
        k = int(k)
        if k < 1:
            raise ValueError(f"steps_per_sync must be >= 1, got {k}")
        self.steps_per_sync = k
        return self

    def set_zero(self, config) -> "Optimizer":
        """Weight-update sharding policy (``parallel.zero.ZeroConfig``):
        stage 1 shards optimizer state over the data axis, stage 2
        additionally reduce-scatters gradients so each replica updates
        only its 1/n shard before a single params all-gather, stage 3
        keeps params sharded at rest with just-in-time per-layer
        gathers inside forward/backward. Composes with
        ``set_steps_per_sync(K)`` — the donated scan carry holds the
        sharded state and XLA overlaps the collectives with the
        neighbouring steps' compute — and with TP ``sharding_rules``
        (ZeRO shards the dims the rules leave free). A no-op off-mesh
        or when the data axis does not split; pass None (or stage 0)
        to disable. Checkpoints save the gathered, unsharded-equivalent
        state, so a run may resume onto a different stage or mesh
        width. The config's ``data_axis`` is reconciled with this
        Optimizer's own (a mismatched axis would silently deactivate
        the policy — ZeRO only makes sense over the axis the batch and
        gradient reduction shard on)."""
        import dataclasses as _dc

        from bigdl_tpu.parallel.zero import ZeroConfig
        if config is not None and not isinstance(config, ZeroConfig):
            raise TypeError(
                f"set_zero expects a parallel.ZeroConfig or None, got "
                f"{type(config).__name__}")
        if config is not None and config.data_axis != self.data_axis:
            config = _dc.replace(config, data_axis=self.data_axis)
        self.zero_config = config if config is not None \
            and config.stage > 0 else None
        self.zero1 = self.zero_config is not None \
            and self.zero_config.stage == 1
        return self

    def set_precision(self, policy, scaler=None) -> "Optimizer":
        """Mixed-precision policy for this run
        (``precision.PrecisionPolicy``, a preset name like
        ``"bf16_mixed"``, or None to revert to f32/Engine defaults).

        The policy threads the whole stack: forward/backward compile in
        ``compute_dtype``, gradients reduce(-scatter) in compute dtype
        under ZeRO, the update runs on f32 weights (the f32 master copy
        when ``param_dtype`` is low-precision), and f16 policies get a
        ``DynamicLossScaler`` (pass ``scaler`` to tune it) whose state
        rides the optimizer-state tree — so ``set_steps_per_sync(K)``
        windows and ZeRO stages 1-3 compose with no further
        configuration, and seeded K=1 vs K=8 runs stay bit-identical
        with the scaler in the scan carry."""
        from bigdl_tpu.precision import DynamicLossScaler, PrecisionPolicy
        if isinstance(policy, str):
            policy = PrecisionPolicy.named(policy)
        if policy is not None and not isinstance(policy, PrecisionPolicy):
            raise TypeError(
                f"set_precision expects a PrecisionPolicy, a preset "
                f"name or None, got {type(policy).__name__}")
        if scaler is not None and not isinstance(scaler,
                                                 DynamicLossScaler):
            raise TypeError(
                f"scaler must be a DynamicLossScaler, got "
                f"{type(scaler).__name__}")
        self._precision = policy
        self._loss_scaler = scaler
        # the compiled validation slot closed over the previous
        # precision regime — drop it like set_model does
        self._dc_eval = None
        return self

    def set_sequence_parallel(self, config) -> "Optimizer":
        """Sequence-parallel attention for this run
        (``parallel.sequence.SeqParallelConfig``, or None for dense).

        The train step traces the model under the policy, so every
        ``MultiHeadAttention`` without an explicit ``ring_axis`` runs
        the configured ring/Ulysses kernel over the named mesh axis —
        activation memory per chip drops to the LOCAL sequence length,
        which is what lets S=128K train at all. Composes with
        ``set_zero`` (weights shard over the data axis, attention over
        the sequence axis) and ``set_steps_per_sync`` (the SP
        collectives live inside the scan body; the windowed dispatch
        boundary stays collective-free). Quiet no-op when the policy
        cannot apply — the ``train/seq_parallel/degree`` gauge reports
        the degree actually achieved."""
        from bigdl_tpu.parallel.sequence import SeqParallelConfig
        if config is not None and not isinstance(config,
                                                 SeqParallelConfig):
            raise TypeError(
                f"set_sequence_parallel expects a "
                f"parallel.SeqParallelConfig or None, got "
                f"{type(config).__name__}")
        self._seq_parallel = config
        return self

    def set_preflight_spec(self, input_spec) -> "Optimizer":
        """Opt-in pre-flight: before any compilation, ``optimize()``
        shape/dtype-checks the model against ``input_spec`` (see
        ``analysis.spec``; strings/None dims are symbolic) under
        ``jax.eval_shape`` and rejects a mis-wired model with a
        layer-path diagnostic instead of a deep XLA trace after a
        30-second compile. Pass None to disable."""
        self._preflight_spec = input_spec
        return self

    def set_drop_module_property(self, drop_percentage: float,
                                 max_drop_percentage: float,
                                 batchsize: int = 100,
                                 warmup_iteration: int = 200) -> "Optimizer":
        """Straggler dropping (Optimizer.scala:276). A synchronous TPU pod
        has no stragglers; accepted for recipe compatibility, does nothing."""
        self._drop_percentage = drop_percentage
        return self

    # -- sharding helpers --------------------------------------------------
    def _multiprocess(self) -> bool:
        """True when the mesh spans more than this process's devices —
        the multi-host regime the reference reached through Spark
        executors (Engine.scala:93-106); arrays must then be assembled
        from per-process local data."""
        return self.mesh is not None and jax.process_count() > 1

    def _data_parallel(self) -> bool:
        """True when the mesh actually splits the batch: a data axis of
        size > 1 (a size-1 axis — what the recipe's mesh builder emits
        when TP/PP consume every device — is the replicated regime)."""
        return self.mesh.shape.get(self.data_axis, 1) > 1

    def _batch_sharding(self, batch_axis: int = 0):
        """Batch layout on the mesh: sharded over the data axis when it
        really splits, else replicated (pure TP/PP meshes).
        ``batch_axis`` is where the batch dimension sits — 0 for a plain
        MiniBatch, 1 for a stacked ``[K, B, ...]`` window buffer (the
        window axis stays unsharded)."""
        spec = jax.sharding.PartitionSpec(
            *([None] * batch_axis + [self.data_axis])) \
            if self._data_parallel() else jax.sharding.PartitionSpec()
        return jax.sharding.NamedSharding(self.mesh, spec)

    def _put_batch(self, arr):
        from bigdl_tpu.dataset.sample import HostBatchedCOO
        if isinstance(arr, HostBatchedCOO):
            # SparseMiniBatch feed (MiniBatch.scala:587): transfer the
            # static-shape COO leaves like any dense batch (batch-dim
            # sharded) and rebuild the jit-compatible BCOO pytree
            if self._multiprocess() and not arr.fixed_nnz:
                raise ValueError(
                    "multi-host sparse batches must pad nnz to a FIXED "
                    "length (SampleToMiniBatch(feature_padding="
                    "PaddingParam(fixed_length=...))): each process "
                    "pads to its own batch max otherwise, and differing "
                    "static shapes desynchronize the SPMD programs")
            vals = self._put_batch(arr.values)
            idx = self._put_batch(arr.indices)
            return arr.to_bcoo(indices=idx, values=vals)
        if self.mesh is not None:
            sh = self._batch_sharding()
            if self._multiprocess() and not self._data_parallel():
                # pure TP/PP mesh (no data axis): the batch is
                # REPLICATED and every process must feed the identical
                # rows — cross-process model collectives then see one
                # consistent batch (megatron's broadcast-input regime)
                from bigdl_tpu.parallel.tp import put_global
                return put_global(np.asarray(arr), sh)
            if self._multiprocess():
                # each process contributes ITS batch rows; the global
                # batch is their concatenation in process order (the
                # role Spark partition locality played). Every process
                # must feed the same row count every step — a ragged
                # final batch would change the global shape mid-run (or
                # desynchronize iteration counts and deadlock the
                # collective), so fail fast instead.
                a = np.asarray(arr)
                expect = self._mp_batch_rows.get(self._stream)
                if expect is None:
                    self._mp_batch_rows[self._stream] = a.shape[0]
                elif a.shape[0] != expect:
                    raise ValueError(
                        f"multi-host {self._stream} batch changed size "
                        f"{expect} -> {a.shape[0]}: local datasets must "
                        "yield equal fixed-size batches per stream (drop "
                        "the remainder or pad the final batch)")
                gshape = (a.shape[0] * jax.process_count(),) + a.shape[1:]
                return jax.make_array_from_process_local_data(sh, a,
                                                              gshape)
            return jax.device_put(jnp.asarray(arr), sh)
        return jnp.asarray(arr)

    def _put_replicated(self, tree):
        if self.mesh is not None:
            sh = jax.sharding.NamedSharding(self.mesh,
                                            jax.sharding.PartitionSpec())
            if self._multiprocess():
                # every process holds the full value (init is
                # seed-identical); put_global assembles the global array
                from bigdl_tpu.parallel.tp import put_global
                return jax.tree.map(lambda a: put_global(a, sh), tree)
            return jax.device_put(tree, sh)
        return tree

    def _active_zero(self):
        """The ZeroConfig in force for THIS run, or None: configured,
        stage > 0, and the mesh's data axis actually splits (LocalOptimizer
        and pure-TP meshes fall back to the dense layout)."""
        cfg = self.zero_config
        return cfg if cfg is not None and cfg.active_on(self.mesh) else None

    def _put_params(self, tree):
        """Params: TP/EP-sharded when rules are given, else replicated —
        except ZeRO stage 3, where params live SHARDED at rest over the
        data axis (composed with any TP rules) and each layer is
        all-gathered just-in-time inside the compiled forward/backward."""
        cfg = self._active_zero()
        if self.mesh is not None and self.sharding_rules is not None:
            from bigdl_tpu.parallel.tp import shard_params, validate_rules
            problems = validate_rules(tree, self.mesh, self.sharding_rules)
            if problems:
                raise ValueError("bad sharding rules:\n" +
                                 "\n".join(problems))
            if cfg is not None and cfg.stage == 3:
                from bigdl_tpu.parallel.zero import shard_zero_tree
                return shard_zero_tree(tree, self.mesh, cfg,
                                       self.sharding_rules)
            return shard_params(tree, self.mesh, self.sharding_rules)
        if cfg is not None and cfg.stage == 3:
            from bigdl_tpu.parallel.zero import shard_zero_tree
            return shard_zero_tree(tree, self.mesh, cfg)
        return self._put_replicated(tree)

    def _put_opt_state(self, tree):
        """Optimizer state (momentum/variance buffers mirror the params
        tree, so the TP rules match their paths too — re.search ignores the
        'momentum/' prefix). Under ZeRO (any stage), every buffer shards
        its first free divisible dim over the data axis — the reference's
        per-node owned shard running the OptimMethod
        (AllReduceParameter.scala:214-303) — with an EXPLICIT sharding on
        every leaf, matching the in-step constraints exactly so donated
        updates never re-lay-out."""
        if self.mesh is None:
            return tree
        cfg = self._active_zero()
        if cfg is not None:
            from bigdl_tpu.parallel.zero import place_zero_opt_state
            return place_zero_opt_state(tree, self.mesh, cfg,
                                        self.sharding_rules)
        if self.sharding_rules is not None:
            from bigdl_tpu.parallel.tp import shard_params
            return shard_params(tree, self.mesh, self.sharding_rules)
        return self._put_replicated(tree)

    # -- windowed driver planning (set_steps_per_sync) ---------------------
    def _window_limit(self, k: int, end_when, device_feed: bool):
        """Run-wide cap on the window size, with the reason for any
        fallback: windowed execution must be OBSERVABLY identical to the
        per-step loop, so anything the host cannot predict before the
        dispatch (loss-dependent or unknown triggers, metric-driven LR
        schedules) forces per-step sync."""
        if k <= 1:
            return 1, ""
        for what, t in (("end trigger", end_when),
                        ("validation trigger", self.validation_trigger),
                        ("checkpoint trigger", self.checkpoint_trigger)):
            if t is None or t.plannable():
                continue
            dep = sorted(t.depends_on) if t.depends_on is not None else None
            why = (f"{what} reads runtime state {dep}" if dep
                   else f"{what} has undeclared dependencies")
            return 1, why + "; per-step sync keeps its semantics exact"
        sched = getattr(self.optim_method, "learning_rate_schedule", None)
        if sched is not None and hasattr(sched, "record_metric"):
            return 1, ("metric-driven LR schedule (Plateau) adjusts per "
                       "step; per-step sync keeps it exact")
        get_trig = getattr(self.train_summary, "get_summary_trigger",
                           None) if self.train_summary is not None else None
        if get_trig is not None and get_trig("Parameters") is not None:
            return 1, ("train-summary Parameters histograms snapshot the "
                       "params of EACH step; per-step sync keeps them "
                       "exact")
        if not device_feed and self._multiprocess():
            return 1, ("multi-host host-feed runs per-step (stacked "
                       "window buffers are single-process)")
        return k, ""

    def _plan_window(self, k_max: int, state, bsz: int, ds_size: int,
                     end_when, shard_size=None) -> int:
        """Largest k <= k_max such that the per-step loop would do NO
        host work (trigger fire, epoch rollover, shard rotation) after
        steps 1..k-1. The k-th step may land ON a boundary: the window
        flushes there and the host replay handles it with the window's
        final (current) params."""
        if k_max <= 1:
            return 1
        n0 = state["neval"]
        ep0 = state["epoch"]
        rec = state["recordsProcessedThisEpoch"]
        spos = ((n0 - 1) * bsz) % shard_size if shard_size else None
        for i in range(1, k_max):
            rec += bsz
            if rec >= ds_size:
                return i  # epoch rollover: shuffle/permutation bookkeeping
            if spos is not None:
                spos += bsz
                if spos >= shard_size:
                    return i  # next shard must rotate in before step i+1
            sim = {"epoch": ep0, "neval": n0 + i,
                   "recordsProcessedThisEpoch": rec}
            for t in (end_when, self.validation_trigger,
                      self.checkpoint_trigger):
                if t is not None and t.peek(sim):
                    return i
        return k_max

    def _window_lrs(self, k: int, state):
        """The k learning rates the per-step loop would have computed,
        via k real ``update_hyper_parameter()`` calls (schedule counters
        advance exactly as they would per-step; the epoch cannot change
        mid-window because windows flush at rollovers)."""
        n0 = state["neval"]
        lrs = []
        for i in range(k):
            self.optim_method.state["neval"] = n0 + i
            lrs.append(self.optim_method.update_hyper_parameter())
        return lrs

    def _prep_io_window(self, batch: MiniBatch):
        """Stage a stacked ``[K, B, ...]`` window batch
        (``dataset.prefetch.stack_minibatches``): like :meth:`_prep_io`,
        but the batch dimension is axis 1, so :meth:`_batch_sharding`
        is asked for the axis-1 layout. Multi-host and sparse batches
        never reach here (the window limiter falls back to per-step,
        where :meth:`_put_batch` owns those regimes)."""
        sh = self._batch_sharding(batch_axis=1) if self.mesh is not None \
            else None

        def put(x):
            if x is None:
                return None
            if isinstance(x, (list, tuple)):
                from bigdl_tpu.utils.table import T as _T
                return _T(*[put(e) for e in x])
            return jnp.asarray(x) if sh is None \
                else jax.device_put(jnp.asarray(x), sh)
        return put(batch.get_input()), put(batch.get_target())

    def _prep_io(self, batch: MiniBatch):
        inp = batch.get_input()
        tgt = batch.get_target()
        if isinstance(inp, (list, tuple)):
            from bigdl_tpu.utils.table import T as _T
            inp = _T(*[self._put_batch(x) for x in inp])
        else:
            inp = self._put_batch(inp)
        if isinstance(tgt, (list, tuple)):
            from bigdl_tpu.utils.table import T as _T
            tgt = _T(*[self._put_batch(x) for x in tgt])
        elif tgt is not None:
            tgt = self._put_batch(tgt)
        return inp, tgt

    # -- checkpointing (DistriOptimizer.checkpoint :433-463) ---------------
    def _cursor_dataset(self):
        """The dataset (possibly behind ``TransformedDataSet`` wrappers —
        walk the ``.base`` chain) that carries a streaming-pipeline
        cursor, or None. Without the unwrap, ``pipe.as_dataset()
        .transform(...)`` would silently lose cursor checkpointing and a
        resumed run would replay already-consumed records."""
        ds = self.dataset
        seen = 0
        while ds is not None and seen < 32:  # cycle guard
            if callable(getattr(ds, "pipeline_state", None)) \
                    and callable(getattr(ds, "restore_pipeline_state",
                                         None)):
                return ds
            ds = getattr(ds, "base", None)
            seen += 1
        return None

    def _checkpoint(self, params, opt_state, model_state):
        from bigdl_tpu.utils.serialization import save_checkpoint
        neval = self.driver_state["neval"]
        suffix = "" if self.is_overwrite else f".{neval}"
        path = os.path.join(self.checkpoint_path, f"checkpoint{suffix}")
        if self.checkpoint_async:
            return self._checkpoint_elastic(path, params, opt_state,
                                            model_state)
        # single-writer in multi-host runs (the reference wrote once
        # from the driver, DistriOptimizer.scala:433-463): every process
        # participates in the collective host materialization inside
        # save_checkpoint, but only process 0 touches the (shared)
        # checkpoint storage — no N× duplicated IO
        writer = not self._multiprocess() or jax.process_index() == 0
        driver_state = {k: v for k, v in self.driver_state.items()}
        # streaming pipelines (datapipe PipelineDataSet) carry a read
        # cursor: checkpoint it alongside the driver counters so resume
        # continues the stream instead of replaying the epoch
        cursor_ds = self._cursor_dataset()
        if cursor_ds is not None and not self._multiprocess():
            # single-process only: the cursor is PROCESS-LOCAL (each
            # process reads its own shard split), but only process 0
            # writes the checkpoint — restoring its cursor onto every
            # process would desync the per-process streams. Multi-host
            # runs keep the pre-cursor resume semantics (epoch replay).
            driver_state["datapipe"] = cursor_ds.pipeline_state()
        save_checkpoint(path, params=params, opt_state=opt_state,
                        model_state=model_state,
                        optim_host_state=self.optim_method.get_state(),
                        driver_state=driver_state,
                        writer=writer)
        if writer:
            logger.info("checkpointed to %s", path)
            if self.checkpoint_keep_last:
                from bigdl_tpu.elastic import prune_checkpoints
                prune_checkpoints(self.checkpoint_path,
                                  self.checkpoint_keep_last)

    def _checkpoint_elastic(self, path, params, opt_state, model_state,
                            sync: bool = False):
        """The per-shard format-3 writer (``bigdl_tpu.elastic``): every
        process snapshots its own shards (no gather), process 0 commits
        the barriered MANIFEST; ``sync=False`` hands the write tail to
        the background writer. Each process contributes ITS datapipe
        cursor, so the manifest carries the full per-process cursor set
        for cross-world-size re-splitting on resume."""
        from bigdl_tpu import elastic
        meta = elastic.run_metadata(
            mesh=self.mesh, data_axis=self.data_axis,
            zero=self._active_zero(), precision=self._precision,
            process_count=jax.process_count() if self._multiprocess()
            else 1)
        cursor_ds = self._cursor_dataset()
        cursor = cursor_ds.pipeline_state() if cursor_ds is not None \
            else None
        elastic.save_checkpoint(
            path, params=params, opt_state=opt_state,
            model_state=model_state,
            optim_host_state=self.optim_method.get_state(),
            driver_state=dict(self.driver_state),
            run_meta=meta, cursor=cursor,
            process_index=jax.process_index() if self._multiprocess()
            else 0,
            process_count=meta["process_count"],
            writer=None if sync else self._ckpt_writer,
            keep_last=self.checkpoint_keep_last)
        logger.info("elastic checkpoint %s to %s",
                    "written" if sync else "enqueued", path)

    def _flush_ckpt_writer(self):
        """Drain the async writer (no-op without one): every resume /
        exit / emergency path calls this so a commit in flight is
        visible before ``find_latest_checkpoint`` runs — and so a
        background write failure surfaces into the classified retry
        loop exactly where the sync writer would have raised."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.flush()

    def _drain_preemption(self, params, opt_state, model_state):
        """The SIGTERM grace path, run at a step boundary (state is
        complete and consistent here): flush the in-flight async write,
        save an EMERGENCY checkpoint synchronously, dump a flight
        bundle, and raise ``Preempted`` — which escapes the retry loop
        (BaseException) so the gang launcher owns the recovery."""
        from bigdl_tpu.elastic import Preempted
        self._grace.count_preemption()
        logger.warning("SIGTERM grace: flushing emergency checkpoint")
        if self.checkpoint_path is not None:
            try:
                self._flush_ckpt_writer()
            except Exception:
                logger.exception("in-flight async write failed during "
                                 "preemption drain; writing emergency "
                                 "checkpoint anyway")
            neval = self.driver_state["neval"]
            suffix = "" if self.is_overwrite else f".{neval}"
            path = os.path.join(self.checkpoint_path,
                                f"checkpoint{suffix}")
            if self.checkpoint_async:
                self._checkpoint_elastic(path, params, opt_state,
                                         model_state, sync=True)
            else:
                self._checkpoint(params, opt_state, model_state)
        telemetry.flight.on_fatal("train/preempt")
        raise Preempted(
            f"SIGTERM at neval {self.driver_state['neval']}: emergency "
            "checkpoint flushed; relaunch resumes from it")

    def _try_resume(self):
        """Latest INTACT checkpoint's state, or None. A checkpoint that
        fails integrity verification (or any load error) is quarantined
        to ``*.corrupt-<pid>`` and the walk continues to the previous
        intact one — without this, a retry loop would re-raise on the
        same corrupt latest dir every attempt and the run could never
        recover. When quarantine itself is impossible (a filesystem
        that cannot rename — remote stores without mv, a read-only
        parent) the load error propagates: silently looping on an
        unremovable bad dir would hang the retry loop."""
        from bigdl_tpu.utils.serialization import (find_latest_checkpoint,
                                                   load_checkpoint,
                                                   quarantine_checkpoint)
        if not self.checkpoint_path:
            return None
        # a commit still on the background writer must land (or its
        # failure surface) before the latest-checkpoint walk
        self._flush_ckpt_writer()
        while True:
            latest = find_latest_checkpoint(self.checkpoint_path)
            if latest is None:
                return None
            try:
                ck = load_checkpoint(latest)
            except Exception as e:
                logger.warning(
                    "checkpoint %s unreadable (%s: %s); quarantining "
                    "and walking back", latest, type(e).__name__, e)
                if quarantine_checkpoint(latest) is None:
                    raise
                continue
            logger.warning("retry: resuming from %s", latest)
            return ck

    # -- validation (DistriOptimizer.scala:607-686) ------------------------
    def _validate(self, params, model_state, eval_step):
        self._stream = "validate"
        try:
            return self._validate_impl(params, model_state, eval_step)
        finally:
            self._stream = "train"

    def _validate_impl(self, params, model_state, eval_step):
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch
        ds = self.validation_dataset
        if hasattr(ds, "eval_batch_fn_on"):
            return self._validate_device_cached(params, model_state, ds)
        it = ds.data(train=False)
        results = None
        # Accept datasets of Samples or of MiniBatches
        batcher = SampleToMiniBatch(self._val_batch_size)
        peek = []
        for el in it:
            peek.append(el)
            break
        if not peek:
            return {}
        import itertools
        full_it = itertools.chain(peek, it)
        if isinstance(peek[0], MiniBatch):
            batches = full_it
        else:
            batches = batcher.apply(full_it)
        for b in batches:
            inp, tgt = self._prep_io(b)
            out = eval_step(params, model_state, inp)
            # multi-host: out/tgt span non-addressable devices; each
            # process scores ITS rows (the reference aggregated
            # per-executor ValidationResults the same way — here the
            # local shard IS this process's data)
            out_np, tgt_np = _local_rows(out), _local_rows(tgt)
            batch_res = [m(out_np, tgt_np)
                         for m in self.validation_methods]
            if results is None:
                results = batch_res
            else:
                results = [r + br for r, br in zip(results, batch_res)]
        if self._multiprocess():
            # reduce ValidationResults across processes (the reference
            # reduce(+)s per-executor results, DistriOptimizer.scala:607)
            results = [_allreduce_result(r) for r in results]
        return self._score_summary(results)

    def _validate_device_cached(self, params, model_state, ds):
        """Trigger-driven validation straight off the HBM cache
        (DeviceCachedArrayDataSet passed to set_validation): one jitted
        sample+forward per batch, zero per-trigger host feed — the
        device-resident form of validation riding the same cached
        distributed dataset as training (DistriOptimizer.scala:607-686).

        Intentionally NOT delegated to Predictor._device_cached_sweep:
        validation fires every trigger, so the compiled sweep must be
        CACHED across calls (the single-slot ``_dc_eval`` below) —
        keep the divisibility guard and trim rules in lockstep with
        predictor.py's one-shot sweep when changing either.
        """
        fn = self._dc_eval[1] if (self._dc_eval is not None
                                  and self._dc_eval[0] is ds) else None
        if fn is None:
            ev_sh = self._batch_sharding() if self.mesh is not None \
                else None

            def _ev(p, m, start, images, labels):
                x, y = ds.eval_batch_fn_on(images, labels, start)
                out, _ = self.model.apply(p, m, x, training=False)
                return out, y

            fn = jax.jit(_ev, out_shardings=(ev_sh, ev_sh))
            self._dc_eval = (ds, fn)
        n, b = ds.size(), ds.batch_size
        if self._multiprocess() and n % b:
            raise ValueError(
                "device-cached multi-host validation needs batch_size to "
                "divide the dataset (a wrapped final batch cannot be "
                "trimmed consistently across processes)")
        results = None
        for start in range(0, n, b):
            out, y = fn(params, model_state, jnp.int32(start),
                        ds.images, ds.labels)
            out_np, tgt_np = _local_rows(out), _local_rows(y)
            valid = min(b, n - start)
            if valid < b:  # eval_batch_fn wraps modulo n; trim the tail
                out_np, tgt_np = out_np[:valid], tgt_np[:valid]
            batch_res = [m(out_np, tgt_np)
                         for m in self.validation_methods]
            results = batch_res if results is None else \
                [r + br for r, br in zip(results, batch_res)]
        if self._multiprocess():
            results = [_allreduce_result(r) for r in results]
        return self._score_summary(results)

    def _score_summary(self, results):
        summary = {}
        for m, r in zip(self.validation_methods, results):
            value, _ = r.result()
            # unique key per method so duplicates (e.g. two Loss instances)
            # don't overwrite each other — first key must stay the FIRST
            # method (driver_state["score"] reads it)
            key, k = m.name, 2
            while key in summary:
                key = f"{m.name}-{k}"
                k += 1
            summary[key] = value
            logger.info("validation %s: %s", key, r)
        return summary

    # -- the loop (optimize(), DistriOptimizer.scala:154-421) --------------
    def optimize(self) -> Module:
        if not Engine.is_initialized():
            Engine.init()
        if self._preflight_spec is not None:
            # pre-flight OUTSIDE the retry loop: a structurally broken
            # model fails identically every attempt, so reject it once,
            # with a layer-path diagnostic, before any init/compile work
            self.model.check(self._preflight_spec, training=True)
        # SIGTERM grace (set_preemption_handler / BIGDL_PREEMPT_GRACE):
        # installed around the whole retry loop so a preemption landing
        # mid-retry still drains through the emergency-checkpoint path
        if self._preempt_grace or os.environ.get(
                "BIGDL_PREEMPT_GRACE") == "1":
            from bigdl_tpu.elastic import GraceHandler
            self._grace = GraceHandler().install()
        try:
            return self._optimize_with_retry()
        finally:
            if self._grace is not None:
                self._grace.uninstall()
                self._grace = None

    def _optimize_with_retry(self) -> Module:
        from bigdl_tpu.faults.retry import backoff_delay, classify
        retries = 0
        while True:
            try:
                return self._optimize_impl()
            except (KeyboardInterrupt,):
                raise
            except Exception as e:  # retry-from-checkpoint loop
                # classified: structural/compile errors (bad types,
                # shape mismatches) fail identically every attempt —
                # fail fast with the first diagnostic; transient
                # IO/runtime errors retry with exponential backoff +
                # jitter so a fleet doesn't stampede whatever just
                # recovered
                retries += 1
                if classify(e) == "fatal" or retries > self.retry_times \
                        or self.checkpoint_path is None:
                    # the error is about to escape the process: dump a
                    # post-mortem bundle (no-op unless flight is armed)
                    telemetry.flight.on_fatal("train/optimizer", e)
                    raise
                _RECOVERIES.inc()
                delay = backoff_delay(retries - 1, self.retry_interval_s,
                                      self.retry_max_interval_s)
                logger.exception(
                    "training failed (%s); retry %d/%d in %.2fs",
                    e, retries, self.retry_times, delay)
                time.sleep(delay)

    def _optimize_impl(self) -> Module:
        model = self.model
        model.training()
        model.ensure_initialized()
        params = model.get_parameters()
        model_state = model.get_state()
        opt_state = self.optim_method.init_state(params)

        resumed = self._try_resume()
        if resumed is not None:
            params = resumed["params"]
            opt_state = resumed["opt_state"]
            model_state = resumed["model_state"]
            self.optim_method.load_state(resumed["optim_host_state"])
            self.driver_state.update(resumed["driver_state"])
            # a checkpointed streaming-pipeline cursor restores the data
            # position (see _checkpoint); popped so the driver counters
            # stay plain ints and a later dataset swap can't reuse it.
            # Multi-process mirrors the _checkpoint guard: the cursor is
            # process-0's PROCESS-LOCAL position — applying it to every
            # process's different shard split would desync the streams,
            # so multi-host resume keeps the epoch-replay fallback.
            cursor = self.driver_state.pop("datapipe", None)
            cursor_ds = self._cursor_dataset()
            if resumed.get("cursors"):
                # format-3 elastic checkpoint: the MANIFEST carries
                # EVERY writing process's cursor — re-split across the
                # CURRENT world size (exact when the count matches, an
                # epoch restart otherwise), which makes multi-process
                # cursor resume a supported path, not an exclusion
                from bigdl_tpu.elastic import resplit_cursor
                cursor = resplit_cursor(
                    resumed["cursors"],
                    jax.process_index() if self._multiprocess() else 0,
                    jax.process_count() if self._multiprocess() else 1)
                if cursor is not None and cursor_ds is not None:
                    cursor_ds.restore_pipeline_state(cursor)
            elif cursor is not None and cursor_ds is not None \
                    and not self._multiprocess():
                cursor_ds.restore_pipeline_state(cursor)
        # epoch/iteration-driven lr schedules read the OptimMethod's
        # state: sync the driver counters in (covers set_state called
        # before set_optim_method, and keeps both views consistent)
        for k in ("epoch", "neval"):
            if k in self.driver_state:
                self.optim_method.state[k] = self.driver_state[k]

        from bigdl_tpu.precision import (MASTER_KEY, SCALER_KEY,
                                         DynamicLossScaler,
                                         PrecisionPolicy)
        policy = self._precision if self._precision is not None \
            else PrecisionPolicy.from_engine()
        scaler = None
        if policy.needs_loss_scaling:
            scaler = self._loss_scaler if self._loss_scaler is not None \
                else DynamicLossScaler()
        if not isinstance(opt_state, dict):  # exotic OptimMethod state
            if policy.needs_master or scaler is not None:
                raise ValueError(
                    "set_precision with master weights / loss scaling "
                    "needs a dict-shaped optimizer state (every shipped "
                    "OptimMethod qualifies)")
        else:
            # a resumed checkpoint already carries these keys; a fresh
            # run (or one resumed from a pre-policy checkpoint, whose
            # params are f32) inserts them here
            if policy.needs_master and MASTER_KEY not in opt_state:
                # the f32 master copy (cast up if the module was built
                # under a low-precision Engine default dtype)
                opt_state[MASTER_KEY] = policy.cast_to_accum(params)
                params = policy.cast_to_param(params)
            if scaler is not None and SCALER_KEY not in opt_state:
                opt_state[SCALER_KEY] = scaler.init_state()
        if not policy.is_noop:
            logger.info("precision policy: %s", policy.describe())
            # value 1 marks the ACTIVE policy; series from earlier runs
            # in this process drop to 0 so diagnose can tell them apart
            for key in _POLICY_INFO._series():
                _POLICY_INFO.set(0.0, **dict(key))
            _POLICY_INFO.set(
                1.0, policy=policy.name,
                param=policy.param_dtype.name,
                compute=policy.compute_dtype.name,
                accum=policy.accum_dtype.name)
            _LOSS_SCALE.set(float(scaler.init_scale) if scaler else 1.0)
            _SKIPPED_STEPS.set(0.0)

        params = self._put_params(params)
        opt_state = self._put_opt_state(opt_state)
        model_state = self._put_replicated(model_state)
        if self.mesh is not None or not policy.is_noop:
            # per-chip memory proof: gauges read the PLACED shard sizes,
            # so the n-fold ZeRO reduction — and the low-precision
            # params/grads shrink — are exported numbers, not claims
            # (train/memory/*_bytes_per_chip; the f32-equivalent
            # "before" lands in train/precision/*_f32_bytes_per_chip)
            from bigdl_tpu.parallel.zero import (record_memory_gauges,
                                                 tree_bytes_per_chip)
            record_memory_gauges(params, opt_state)
            if not policy.is_noop:
                _PARAMS_F32_BYTES.set(tree_bytes_per_chip(
                    params, floating_as=jnp.float32))
                _OPT_F32_BYTES.set(tree_bytes_per_chip(
                    opt_state, floating_as=jnp.float32))

        step = build_train_step(model, self.criterion, self.optim_method,
                                gradient_clip=self._gradient_clip,
                                zero=self._active_zero(), mesh=self.mesh,
                                sharding_rules=self.sharding_rules,
                                precision=policy, loss_scaler=scaler,
                                seq_parallel=self._seq_parallel)
        ev_sh = self._batch_sharding() if self.mesh is not None else None
        # validation runs under the policy only when the user OPTED IN
        # via set_precision — the legacy Engine dtype knobs never cast
        # eval (pre-policy validation always scored the f32 forward)
        eval_step = build_eval_step(model, ev_sh,
                                    precision=self._precision)
        track_scaler = scaler is not None

        ds_size = self.dataset.size()
        state = self.driver_state
        # Device-cached feed (DeviceCachedArrayDataSet): the batch is
        # sampled + augmented INSIDE the jitted step — zero per-step
        # host->device traffic (the HBM form of the reference's decoded
        # executor cache, DataSet.scala CachedDistriDataSet:240).
        rotating = getattr(self.dataset, "rotating", False)
        device_feed = rotating or hasattr(self.dataset, "batch_fn")
        if rotating:
            # rotating HBM shard cache (RotatingDeviceDataSet): the slot
            # arrays MUST be step arguments — a closure would bake them
            # in as compile-time constants and train on the first shard
            # forever; as arguments, each rotation is a plain rebind of
            # the one compiled step
            ds = self.dataset
            tmpl = ds.template

            def _fused_rot(p, o, m, key, lr, ep, pos, images, labels):
                kb, kr = jax.random.split(key)
                x, y = tmpl.batch_fn_on(images, labels, kb,
                                        epoch=ep, pos=pos)
                return step(p, o, m, kr, lr, x, y)

            fused_step = jax.jit(_fused_rot, donate_argnums=(0, 1, 2))
            data_iter = None
        elif device_feed:
            ds = self.dataset
            # epoch-exact feed: the global iteration index drives a
            # per-epoch permutation inside batch_fn (DataSet.scala:240
            # shuffle semantics); datasets without sample_indices keep
            # the rng-only contract
            epoch_exact = hasattr(ds, "sample_indices")
            # on a mesh spanning processes the cache arrays are global
            # arrays with non-addressable shards — jit cannot close over
            # those; pass them as arguments (batch_fn_on) when available
            feed_by_arg = hasattr(ds, "batch_fn_on")

            if feed_by_arg:
                def _fused(p, o, m, key, lr, ep, pos, images, labels):
                    kb, kr = jax.random.split(key)
                    x, y = ds.batch_fn_on(images, labels, kb,
                                          epoch=ep, pos=pos) \
                        if epoch_exact else \
                        ds.batch_fn_on(images, labels, kb)
                    return step(p, o, m, kr, lr, x, y)
            else:
                def _fused(p, o, m, key, lr, ep, pos):
                    kb, kr = jax.random.split(key)
                    x, y = ds.batch_fn(kb, epoch=ep, pos=pos) \
                        if epoch_exact else ds.batch_fn(kb)
                    return step(p, o, m, kr, lr, x, y)

            # donate like build_train_step does — inner-jit donation is
            # ignored when traced inside an outer jit
            fused_step = jax.jit(_fused, donate_argnums=(0, 1, 2))
            data_iter = None
        else:
            data_iter = self.dataset.data(train=True)
        end_when = self.end_when
        if end_when is None:
            from bigdl_tpu.optim.trigger import max_epoch
            end_when = max_epoch(10)

        # -- windowed driver setup (set_steps_per_sync) -------------------
        # plan_bsz: the per-step record count windows are planned with
        # (device feeds are exact; host feeds re-check actual sizes while
        # gathering a window)
        plan_bsz = self.dataset.batch_size if (rotating or device_feed) \
            else self.batch_size
        k_cap, why = self._window_limit(self.steps_per_sync, end_when,
                                        rotating or device_feed)
        if k_cap < self.steps_per_sync:
            logger.info("steps_per_sync=%d: falling back to per-step "
                        "sync — %s", self.steps_per_sync, why)
        shard_size = self.dataset.rot.shard_size if rotating else None
        window_fn = None       # ONE jitted program per feed path; jax's
        host_window_fn = None  # compile cache keys it by (k, shapes)
        if k_cap > 1 and (rotating or device_feed):
            modulus = shard_size if rotating else ds_size
            if rotating:
                def _feed(arrs, kb, ep, pos):
                    return tmpl.batch_fn_on(arrs[0], arrs[1], kb,
                                            epoch=ep, pos=pos)
            elif feed_by_arg:
                if epoch_exact:
                    def _feed(arrs, kb, ep, pos):
                        return ds.batch_fn_on(arrs[0], arrs[1], kb,
                                              epoch=ep, pos=pos)
                else:
                    def _feed(arrs, kb, ep, pos):
                        return ds.batch_fn_on(arrs[0], arrs[1], kb)
            else:
                if epoch_exact:
                    def _feed(arrs, kb, ep, pos):
                        return ds.batch_fn(kb, epoch=ep, pos=pos)
                else:
                    def _feed(arrs, kb, ep, pos):
                        return ds.batch_fn(kb)

            def _window_dev(p, o, m, keys, lrs, ep0, pos0, *arrs):
                # K fused steps: the (epoch, pos) sample cursor advances
                # in the scan carry (all values stay < 2*modulus — no
                # int32 overflow however long the run); losses come back
                # as ONE length-K vector
                def body(carry, sl):
                    p, o, m, ep, pos = carry
                    key, lr = sl
                    kb, kr = jax.random.split(key)
                    x, yb = _feed(arrs, kb, ep, pos)
                    p, o, m, loss = step(p, o, m, kr, lr, x, yb)
                    pos = pos + plan_bsz
                    ep = ep + pos // modulus
                    pos = pos % modulus
                    return (p, o, m, ep, pos), loss
                (p, o, m, _, _), losses = jax.lax.scan(
                    body, (p, o, m, ep0, pos0), (keys, lrs))
                return p, o, m, losses

            window_fn = telemetry.programs.maybe_wrap_jitted(
                train_program_name(model, "window"), "train",
                jax.jit(_window_dev, donate_argnums=(0, 1, 2)),
                donation="params,opt_state,model_state",
                scan_length_for=lambda a, kw: int(a[3].shape[0]),
                items_for=lambda a, kw: int(a[3].shape[0]) * plan_bsz)
        elif k_cap > 1:
            def _host_window_items(a, kw):
                # xs is the [K, B, ...] stacked window: K*B records
                leaf = jax.tree_util.tree_leaves(a[5])[0]
                return int(leaf.shape[0]) * int(leaf.shape[1])

            host_window_fn = telemetry.programs.maybe_wrap_jitted(
                train_program_name(model, "window"), "train",
                make_host_window(step),
                donation="params,opt_state,model_state",
                scan_length_for=lambda a, kw: int(a[3].shape[0]),
                items_for=_host_window_items)

        def device_cursor_args():
            """Step arguments for the device-resident feeds at the
            CURRENT ``state['neval']`` — the ONE place the cursor
            convention lives, shared by the per-step and windowed
            dispatches (divergence here would silently split K=1 vs
            K>1 semantics). neval starts at 1 (reference convention);
            the sample stream is 0-based so epoch boundaries line up
            with recordsProcessedThisEpoch rollover; the cursor is
            decomposed HERE with exact Python integers, so no
            device-int overflow however long the run."""
            if rotating:
                visit, sp = self.dataset.shard_cursor(state["neval"])
                return (jnp.int32(visit), jnp.int32(sp),
                        self.dataset.images, self.dataset.labels)
            e0, p0 = divmod((state["neval"] - 1) * plan_bsz, ds_size)
            args = (jnp.int32(e0), jnp.int32(p0))
            if feed_by_arg:
                args += (self.dataset.images, self.dataset.labels)
            return args

        pending: List[MiniBatch] = []  # host batches pulled ahead
        warned_unstackable = False  # log the data-dependent fallback once

        def pull_batch() -> MiniBatch:
            b = pending.pop(0) if pending else next(data_iter)
            if not isinstance(b, MiniBatch):
                raise ValueError(
                    "dataset must yield MiniBatch; add SampleToMiniBatch")
            return b

        def post_step(loss_f, lr, bsz_i, throughput):
            """One step's worth of host bookkeeping. The per-step loop
            runs it after every step; the windowed driver REPLAYS it K
            times after the single window fetch, so counters, triggers,
            epoch rollovers and summaries observe the identical
            per-step sequence either way."""
            nonlocal data_iter
            if rotating:
                # the window/loss fetch completed this step; stream the
                # next shard piece now (alternation rule) and rotate
                # slots at shard boundaries
                self.dataset.after_step(state["neval"])
            state["neval"] += 1
            self.optim_method.state["neval"] = state["neval"]
            state["recordsProcessedThisEpoch"] += bsz_i
            state["Loss"] = loss_f
            state["LearningRate"] = lr
            state["Throughput"] = throughput
            logger.info(
                "Epoch %d iter %d: loss %.4f lr %.5f throughput %.1f rec/s",
                state["epoch"], state["neval"] - 1, loss_f, lr, throughput)

            if self.train_summary is not None:
                self.train_summary.add_scalar("Loss", loss_f, state["neval"])
                self.train_summary.add_scalar("LearningRate", lr,
                                              state["neval"])
                self.train_summary.add_scalar("Throughput", throughput,
                                              state["neval"])
                # per-parameter histograms, opt-in via trigger
                # (TrainSummary.scala:64; DistriOptimizer.scala:464-498)
                get_trig = getattr(self.train_summary,
                                   "get_summary_trigger", None)
                ptrig = get_trig("Parameters") if get_trig else None
                if ptrig is not None and ptrig(state):
                    flat, _ = jax.tree_util.tree_flatten_with_path(params)
                    for path, leaf in flat:
                        tag = "/".join(
                            str(getattr(k, "key", getattr(k, "idx", k)))
                            for k in path)
                        self.train_summary.add_histogram(
                            tag, np.asarray(leaf), state["neval"])

            # epoch rollover (DistriOptimizer.scala:368-380). Carry the
            # overshoot: when batch_size does not divide ds_size a batch
            # straddles the epoch boundary, and resetting to 0 would make
            # the driver's epoch drift from the sample stream's true
            # permutation epochs (epoch-driven lr schedules / triggers
            # would fire progressively late)
            while state["recordsProcessedThisEpoch"] >= ds_size:
                # while, not if: one batch can span several epochs when
                # batch_size > ds_size
                state["epoch"] += 1
                self.optim_method.state["epoch"] = state["epoch"]
                state["recordsProcessedThisEpoch"] -= ds_size
                if not device_feed and not getattr(
                        self.dataset, "continuous_stream", False):
                    # a restartable iterator begins a FRESH permutation,
                    # so the overshoot carry would skip its tail — reset
                    # to 0; continuous streams (device feed, the
                    # ImageFolder _IndexStream) keep the carry, which
                    # tracks their true permutation boundary exactly
                    state["recordsProcessedThisEpoch"] = 0
                    self.dataset.shuffle()
                    data_iter = self.dataset.data(train=True)

            # validation / checkpoint triggers (:382-411). Windows flush
            # at every plannable trigger boundary, so in K>1 mode these
            # can only fire on the LAST replayed step — where params are
            # exactly the window's (current) outputs.
            if (self.validation_trigger is not None
                    and self.validation_trigger(state)):
                with telemetry.span("optimizer/validate",
                                    step=state["neval"]):
                    scores = self._validate(params, model_state,
                                            eval_step)
                if scores:
                    # The first method's result drives maxScore/Plateau —
                    # a max() across heterogeneous methods (e.g. Top1 vs
                    # Loss) would act on the wrong number
                    # (DistriOptimizer.scala:382-397 uses head).
                    state["score"] = next(iter(scores.values()))
                    sched = getattr(self.optim_method,
                                    "learning_rate_schedule", None)
                    if sched is not None and hasattr(sched, "record_metric"):
                        sched.record_metric(state["score"])
                    if self.validation_summary is not None:
                        for k, v in scores.items():
                            self.validation_summary.add_scalar(
                                k, v, state["neval"])
            if (self.checkpoint_trigger is not None
                    and self.checkpoint_trigger(state)):
                with telemetry.span("optimizer/checkpoint",
                                    step=state["neval"]):
                    self._checkpoint(params, opt_state, model_state)

        wall_start = time.time()
        while not end_when(state):
            if self._grace is not None and self._grace.requested():
                # SIGTERM grace: step boundary, state consistent —
                # flush the emergency checkpoint and exit via Preempted
                self._drain_preemption(params, opt_state, model_state)
            # scripted worker-death site (ExceptionTest's role): a chaos
            # schedule can raise (exercising the classified retry loop)
            # or SIGKILL here, keyed on the driver counters; disarmed
            # it's one flag check
            faults.point("train/step", neval=state["neval"],
                         epoch=state["epoch"])
            k_now = 1 if k_cap <= 1 else self._plan_window(
                k_cap, state, plan_bsz, ds_size, end_when,
                shard_size=shard_size)
            t0 = time.time()
            window_batches = None
            if k_now > 1 and not (rotating or device_feed):
                # host feed: gather a window of stackable equal-shape
                # prefetched batches; a shape change, sparse leaves, the
                # epoch boundary or exhaustion close the window early
                first = pull_batch()
                window_batches = [first]
                if not _window_stackable(first) and not warned_unstackable:
                    # config-level fallbacks log via _window_limit; this
                    # DATA-dependent one must be visible too, or a user
                    # chases a phantom "K=8 is no faster" regression
                    warned_unstackable = True
                    logger.info(
                        "steps_per_sync=%d: batches are not window-"
                        "stackable (sparse or device-resident leaves) — "
                        "running per-step", self.steps_per_sync)
                if _window_stackable(first):
                    sig = batch_signature(first)
                    rec_sim = (state["recordsProcessedThisEpoch"]
                               + first.size())

                    def boundary_after(steps_done, rec):
                        # _plan_window simulated with the CONFIGURED
                        # batch size; datasets may yield other sizes,
                        # so re-peek the plannable triggers with the
                        # ACTUAL accumulated record counts — a fire
                        # after the just-gathered step ends the window
                        sim = {"epoch": state["epoch"],
                               "neval": state["neval"] + steps_done,
                               "recordsProcessedThisEpoch": rec}
                        return any(t is not None and t.peek(sim)
                                   for t in (end_when,
                                             self.validation_trigger,
                                             self.checkpoint_trigger))

                    while len(window_batches) < k_now \
                            and rec_sim < ds_size \
                            and not boundary_after(len(window_batches),
                                                   rec_sim):
                        try:
                            b = pull_batch()
                        except StopIteration:
                            break
                        if not _window_stackable(b) \
                                or batch_signature(b) != sig:
                            pending.append(b)
                            break
                        window_batches.append(b)
                        rec_sim += b.size()
                k_now = len(window_batches)

            if k_now > 1:
                # ---- fused window: ONE dispatch, ONE host sync ------
                if rotating or device_feed:
                    sizes = [plan_bsz] * k_now
                    wargs = device_cursor_args()
                    t_data = time.time() - t0
                else:
                    sizes = [b.size() for b in window_batches]
                    stacked = stack_minibatches(window_batches)
                    inp, tgt = self._prep_io_window(stacked)
                    # close the staging window before dispatch, exactly
                    # like the per-step path (sanctioned window-boundary
                    # sync)
                    jax.block_until_ready((inp, tgt))  # bigdl: disable=sync-in-loop
                    t_data = time.time() - t0
                # LR schedule + RNG key prep sit BETWEEN the phase
                # windows, exactly where the per-step loop runs them —
                # K=1 and K>1 data_wait/compute stay comparable
                lr_list = self._window_lrs(k_now, state)
                keys = jnp.stack([RandomGenerator.next_key()
                                  for _ in range(k_now)])
                # scan xs are strongly typed, unlike the per-step path's
                # weak Python-float lr: stage in default_dtype so the
                # update math promotes identically (a strong f32 lr
                # against bf16 master params would widen the carry)
                lrs = jnp.asarray(lr_list, Engine.default_dtype())
                t1 = time.time()
                if rotating or device_feed:
                    params, opt_state, model_state, losses = window_fn(
                        params, opt_state, model_state, keys, lrs, *wargs)
                else:
                    params, opt_state, model_state, losses = \
                        host_window_fn(params, opt_state, model_state,
                                       keys, lrs, inp, tgt)
                # THE one sync per window: the losses fetch only gates
                # the loss path, so close the timing window on the full
                # outputs first (sanctioned window-boundary sync)
                jax.block_until_ready((params, opt_state, model_state))  # bigdl: disable=sync-in-loop
                loss_vals = _losses_list(losses, k_now)
                t_compute = time.time() - t1
                if track_scaler and telemetry.enabled():
                    _record_scaler_gauges(opt_state)
                if telemetry.enabled():
                    # per-WINDOW records (amortized granularity — see
                    # docs/performance.md); phase SUMS still equal the
                    # Metrics sums, so diagnose's invariant holds
                    telemetry.record("optimizer/data_wait", t_data,
                                     step=state["neval"])
                    telemetry.record("optimizer/compute", t_compute,
                                     step=state["neval"], steps=k_now)
                _STEP_COUNT.inc(k_now)
                _RECORD_COUNT.inc(sum(sizes))
                self.metrics.add("data time", t_data)
                self.metrics.add("computing time", t_compute)
                if telemetry.programs.enabled() and t_compute > 0:
                    # the measured window rate turns the registered
                    # analytic FLOPs into achieved-TFLOPs/MFU gauges
                    telemetry.programs.record_rate(
                        train_program_name(model, "window"),
                        sum(sizes) / t_compute)
                telemetry.flight.note_metrics({"step": state["neval"]})
                telemetry.agg.maybe_ship()
                rate = sum(sizes) / max(1e-9, t_data + t_compute)
                for i in range(k_now):
                    post_step(loss_vals[i], lr_list[i], sizes[i], rate)
                continue

            # ---- classic per-step path (k == 1) ---------------------
            if rotating or device_feed:
                bsz = self.dataset.batch_size
                step_args = device_cursor_args()
                run_step = fused_step
            else:
                batch = window_batches[0] if window_batches \
                    else pull_batch()
                inp, tgt = self._prep_io(batch)
                # device_put above only DISPATCHED the transfer; without
                # this barrier the copy time would silently migrate into
                # t_compute and the data-vs-compute attribution would lie
                # (sanctioned per-step sync; steps_per_sync amortizes it)
                jax.block_until_ready((inp, tgt))  # bigdl: disable=sync-in-loop
                bsz = batch.size()
                step_args = (inp, tgt)
                run_step = step
            t_data = time.time() - t0
            # trace carries the EXACT t_data the Metrics dump reports,
            # so diagnose's phase attribution and Metrics.summary()
            # agree to the digit (enabled() hoist: the disabled path
            # must do no dict/label work in the hot loop)
            if telemetry.enabled():
                telemetry.record("optimizer/data_wait", t_data,
                                 step=state["neval"])

            lr = self.optim_method.update_hyper_parameter()
            rng = RandomGenerator.next_key()
            t1 = time.time()
            params, opt_state, model_state, loss = run_step(
                params, opt_state, model_state, rng, lr, *step_args)
            # fetching the loss scalar only gates on the loss VALUE; the
            # param/optimizer updates it does not depend on may still be
            # in flight, so close the timing window on the full outputs
            # (sanctioned per-step sync; steps_per_sync amortizes it)
            jax.block_until_ready((params, opt_state, model_state))  # bigdl: disable=sync-in-loop
            loss_f = _to_scalar(loss)
            t_compute = time.time() - t1
            if track_scaler and telemetry.enabled():
                _record_scaler_gauges(opt_state)
            if telemetry.enabled():
                telemetry.record("optimizer/compute", t_compute,
                                 step=state["neval"])
            _STEP_COUNT.inc()
            _RECORD_COUNT.inc(bsz)
            self.metrics.add("data time", t_data)
            self.metrics.add("computing time", t_compute)
            if telemetry.programs.enabled() and t_compute > 0:
                telemetry.programs.record_rate(
                    train_program_name(model), bsz / t_compute)
            telemetry.flight.note_metrics({"step": state["neval"]})
            telemetry.agg.maybe_ship()
            post_step(loss_f, lr, bsz,
                      bsz / max(1e-9, t_data + t_compute))

        # a run shorter than the ship interval must still leave its
        # end-of-run totals in the fleet snapshot file
        telemetry.agg.maybe_ship(force=True)
        logger.info("training done in %.1fs; %s", time.time() - wall_start,
                    self.metrics.summary())
        # the run is over: a checkpoint still on the background writer
        # must land (or surface its failure) before optimize() returns
        self._flush_ckpt_writer()
        # write trained params back to the stateful module (multi-host
        # safe: ZeRO-1 can leave updated params data-sharded, and a
        # spanning shard is not plain-readable — host_value reshards).
        # Under a master-weights policy the f32 MASTER copy is the
        # canonical result — the at-rest low-precision params are its
        # rounding, and downstream consumers (export, further finetunes)
        # want the full-precision weights.
        from bigdl_tpu.utils.serialization import host_value
        final_params = opt_state[MASTER_KEY] \
            if isinstance(opt_state, dict) and MASTER_KEY in opt_state \
            else params
        model.set_parameters(jax.tree.map(host_value, final_params))
        model.set_state(jax.tree.map(host_value, model_state))
        return model


class LocalOptimizer(Optimizer):
    """Single-process training on whatever single device jax default is
    (optim/LocalOptimizer.scala:41)."""

    def __init__(self, model, dataset, criterion, batch_size: int = 32):
        super().__init__(model, dataset, criterion, batch_size, mesh=None)


class DistriOptimizer(Optimizer):
    """Synchronous data-parallel training over the Engine mesh
    (optim/DistriOptimizer.scala:728)."""

    def __init__(self, model, dataset, criterion, batch_size: int = 32,
                 mesh: Optional[jax.sharding.Mesh] = None):
        super().__init__(model, dataset, criterion, batch_size,
                         mesh=mesh or Engine.mesh())
