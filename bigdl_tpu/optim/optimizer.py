"""Optimizer — the training runtime (BigDL optim/Optimizer.scala:42,
LocalOptimizer.scala:41, DistriOptimizer.scala:88-421).

TPU-first translation of the reference's two-level data parallelism:

- intra-node thread clones (DistriOptimizer.scala:116-118) -> the per-chip
  batch dimension; XLA vectorizes.
- AllReduceParameter's reduce-scatter/optimizer/all-gather over Spark
  BlockManager (AllReduceParameter.scala:214-303) -> ONE compiled step:
  forward + backward + gradient mean over the `data` mesh axis + optimizer
  update, jitted together so XLA fuses the collective into the backward pass
  and overlaps it with compute over ICI.
- The Spark driver loop (iteration barrier, triggers, metrics, checkpoint)
  -> this host Python loop.

The straggler-dropping machinery (DistriOptimizer.scala:337-365) has no TPU
equivalent — a synchronous pod has no stragglers — so ``set_drop_module_
property`` is accepted as a documented no-op for API parity. The
retry-from-checkpoint loop (DistriOptimizer.scala:789-855) IS kept.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.nn.module import AUX_LOSS_KEY, Criterion, Module
from bigdl_tpu.optim.optim_method import OptimMethod, SGD
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import ValidationMethod
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random import RandomGenerator

logger = logging.getLogger("bigdl_tpu")

# process-wide training throughput counters (telemetry registry; the
# per-run phase times ride the Metrics histograms below)
_STEP_COUNT = telemetry.counter("train/optimizer/steps",
                                "optimizer steps completed")
_RECORD_COUNT = telemetry.counter("train/optimizer/records",
                                  "training records processed")


class Metrics:
    """Named counters (optim/Metrics.scala:31) — host dict, no Spark
    accumulators needed.

    Migrated onto the telemetry registry: every ``add`` also lands in a
    ``train/optimizer/<metric>`` histogram, so the TensorBoard /
    Prometheus / JSONL exporters and ``tools.diagnose`` see the SAME
    numbers ``summary()`` prints. The local per-run list (and the
    ``summary()`` format) are unchanged — this class stays the per-run
    view, the registry the process-wide one."""

    def __init__(self, registry=None):
        self.values: Dict[str, List[float]] = {}
        self._registry = registry if registry is not None \
            else telemetry.registry()
        self._instruments: Dict[str, Any] = {}

    @staticmethod
    def _slug(name: str) -> str:
        """'data time' -> 'data_time' (the family/component/metric
        charset the telemetry-audit gate enforces)."""
        import re
        return re.sub(r"[^a-z0-9_]+", "_", name.lower()).strip("_")

    def add(self, name: str, value: float):
        self.values.setdefault(name, []).append(value)
        h = self._instruments.get(name)
        if h is None:
            h = self._registry.histogram(
                f"train/optimizer/{self._slug(name)}",
                f"Optimizer Metrics series {name!r} (seconds)")
            self._instruments[name] = h
        h.observe(value)

    def summary(self) -> str:
        parts = []
        for k, v in self.values.items():
            parts.append(f"{k}: avg {np.mean(v):.4f}s over {len(v)}")
        return "; ".join(parts)


def _collect_aux_losses(state_tree):
    """Sum every reserved ``AUX_LOSS_KEY`` leaf in a model-state tree (MoE
    load-balance terms, nn/moe.py). Only the dunder-namespaced key joins
    the objective — a user state entry named "aux_loss" does not.
    Differentiable — called inside loss_fn."""
    total = 0.0
    flat, _ = jax.tree_util.tree_flatten_with_path(state_tree)
    for path, leaf in flat:
        keys = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
        if keys and keys[-1] == AUX_LOSS_KEY:
            total = total + leaf
    return total


def _to_scalar(x) -> float:
    """float(loss) that also works on multi-host global arrays (a fully
    replicated value is readable from any addressable shard)."""
    try:
        return float(x)
    except Exception:
        return float(np.asarray(
            jax.device_get(x.addressable_shards[0].data)))


def _allreduce_result(r):
    """Sum a ValidationResult across processes: gather (numerator,
    count) and rebuild, so every host reports the GLOBAL score."""
    from jax.experimental import multihost_utils

    from bigdl_tpu.optim.validation import AccuracyResult, LossResult

    value, count = r.result()
    arr = multihost_utils.process_allgather(
        np.array([value * count, count], np.float64))
    num, cnt = np.asarray(arr).reshape(-1, 2).sum(0)
    if isinstance(r, AccuracyResult):
        return AccuracyResult(int(round(num)), int(cnt))
    if isinstance(r, LossResult):
        return LossResult(float(num), int(cnt))
    return r  # unknown result type: keep the local value


def _local_rows(x) -> np.ndarray:
    """Materialize a (possibly multi-host, batch-sharded) array's rows
    held by THIS process, in batch order; plain arrays pass through."""
    try:
        return np.asarray(x)
    except Exception:
        shards = sorted(x.addressable_shards,
                        key=lambda s: (s.index[0].start or 0))
        seen, parts = set(), []
        for s in shards:  # dedupe replicated copies across local devices
            key = tuple((sl.start, sl.stop) for sl in s.index)
            if key in seen:
                continue
            seen.add(key)
            parts.append(np.asarray(jax.device_get(s.data)))
        return np.concatenate(parts)


def build_train_step(module: Module, criterion: Criterion,
                     optim_method: OptimMethod,
                     aux_loss_weight: float = 0.01,
                     gradient_clip=None):
    """The compiled hot path: loss + grad + update in one jit.

    Gradient normalization matches the reference (grads averaged over the
    global batch, DistriOptimizer.scala:296-310 divides by numFinished);
    param_scales implements layer-wise scaling / freeze. Auxiliary losses
    the model emits through its state (MoE load balancing) join the
    objective with weight ``aux_loss_weight`` so they actually produce
    router gradients. ``gradient_clip`` = ("constant", min, max) or
    ("l2norm", max_norm) applies the reference's gradient clipping
    (Optimizer.scala setConstantGradientClipping /
    setGradientClippingByl2Norm) to the aggregated gradients before the
    update — the global-L2 form is what keeps edge-of-stability recipes
    (classic PTB LSTM at lr 1.0) convergent.
    """
    if gradient_clip is not None and gradient_clip[0] not in (
            "constant", "l2norm"):
        raise ValueError(
            f"gradient_clip kind must be 'constant' or 'l2norm', got "
            f"{gradient_clip[0]!r}")

    def step(params, opt_state, model_state, rng, lr, inputs, targets):
        cdtype = Engine.compute_dtype()
        ddtype = Engine.default_dtype()

        def maybe_cast(tree, dtype):
            if cdtype == ddtype:
                return tree
            return jax.tree.map(
                lambda a: a.astype(dtype)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)

        def loss_fn(p):
            # mixed precision: compute fwd/bwd in compute_dtype (bf16 on
            # TPU — the analogue of the reference's fp16 gradient
            # compression, FP16CompressedTensor.scala), master params and
            # the update stay in default_dtype.
            p_c = maybe_cast(p, cdtype)
            x_c = maybe_cast(inputs, cdtype)
            out, new_mstate = module.apply(p_c, model_state, x_c,
                                           training=True, rng=rng)
            out = maybe_cast(out, ddtype)
            loss = criterion.apply(out, targets)
            reg = module.regularization_loss(p)
            aux = _collect_aux_losses(new_mstate)
            return loss + reg + aux_loss_weight * aux, (new_mstate, loss)

        grads, (new_mstate, data_loss) = jax.grad(
            loss_fn, has_aux=True)(params)
        scales = module.param_scales(params)
        if any(s != 1.0 for s in jax.tree.leaves(scales)):
            grads = jax.tree.map(lambda g, s: g * s, grads, scales)
        if gradient_clip is not None:
            if gradient_clip[0] == "constant":
                lo, hi = gradient_clip[1], gradient_clip[2]
                grads = jax.tree.map(lambda g: jnp.clip(g, lo, hi),
                                     grads)
            else:  # global L2 norm
                nrm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree.leaves(grads)))
                scale = jnp.minimum(
                    1.0, gradient_clip[1] / jnp.maximum(nrm, 1e-12))
                grads = jax.tree.map(
                    lambda g: g * scale.astype(g.dtype), grads)
        new_params, new_opt = optim_method.update(grads, opt_state, params,
                                                  lr)
        return new_params, new_opt, new_mstate, data_loss

    return jax.jit(step, donate_argnums=(0, 1, 2))


def build_eval_step(module: Module, out_sharding=None):
    """``out_sharding`` pins the output layout (batch-sharded over the
    data axis on a mesh): GSPMD is otherwise free to replicate the
    output, and multi-host scoring slices each process's LOCAL rows —
    those must be the rows that process fed."""
    def eval_step(params, model_state, inputs):
        out, _ = module.apply(params, model_state, inputs, training=False)
        return out

    return jax.jit(eval_step, out_shardings=out_sharding)


class Optimizer:
    """Driver loop + fluent config surface (optim/Optimizer.scala:42).

    One class covers the reference's LocalOptimizer (single chip) and
    DistriOptimizer (multi-chip): the difference is only the mesh the batch
    is laid out over.
    """

    def __init__(self, model: Module, dataset: AbstractDataSet,
                 criterion: Criterion, batch_size: int = 32,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 data_axis: str = "data",
                 sharding_rules=None, zero1: bool = False):
        self.model = model
        self.dataset = dataset
        self.criterion = criterion
        self.batch_size = batch_size
        self.mesh = mesh
        self.data_axis = data_axis
        # tensor/expert-parallel param layout (parallel/tp.py rules);
        # None = fully replicated params (pure DP, the reference's layout)
        self.sharding_rules = sharding_rules
        # ZeRO-1: optimizer state sharded over the data axis — the direct
        # analogue of the reference's per-node OWNED weight shard running
        # the OptimMethod (AllReduceParameter.scala:214-303)
        self.zero1 = zero1
        self.optim_method: OptimMethod = SGD()
        self.end_when: Trigger = None
        # validation
        self.validation_trigger: Optional[Trigger] = None
        self.validation_dataset: Optional[AbstractDataSet] = None
        self.validation_methods: Optional[List[ValidationMethod]] = None
        # checkpoint
        self.checkpoint_trigger: Optional[Trigger] = None
        self.checkpoint_path: Optional[str] = None
        self.is_overwrite = False
        # summaries
        self.train_summary = None
        self.validation_summary = None
        # failure retry (DistriOptimizer.scala:789-855)
        # multi-host fixed-batch guard, tracked PER STREAM: validation may
        # legitimately use a different batch size than training
        self._mp_batch_rows: Dict[str, int] = {}
        self._stream = "train"
        self.retry_times = int(os.environ.get("BIGDL_FAILURE_RETRY_TIMES", 5))
        self.retry_interval_s = float(
            os.environ.get("BIGDL_FAILURE_RETRY_INTERVAL", 1.0))
        self.metrics = Metrics()
        # gradient clipping (Optimizer.scala setConstantGradientClipping
        # / setGradientClippingByl2Norm); None = off
        self._gradient_clip = None
        # opt-in pre-flight shape check (analysis/shapecheck.py); None =
        # off. Set via set_preflight_spec.
        self._preflight_spec = None
        # single-slot (dataset, jitted fn) cache for device-cached
        # validation — replacing the validation dataset must free the
        # old split's HBM-resident arrays, not pin them forever
        self._dc_eval: Optional[tuple] = None
        self.driver_state: Dict[str, Any] = {"epoch": 1, "neval": 1,
                                             "recordsProcessedThisEpoch": 0}
        self._drop_percentage = 0.0  # accepted, no-op on TPU

    # -- fluent config (Optimizer.scala:120-343) ---------------------------
    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_method = method
        return self

    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_validation(self, trigger: Trigger, dataset: AbstractDataSet,
                       methods: Sequence[ValidationMethod],
                       batch_size: Optional[int] = None) -> "Optimizer":
        # a DeviceCachedArrayDataSet bakes its batch size into the
        # compiled sample+forward — a conflicting request would be
        # silently dropped, so reject it up front, BEFORE any state
        # mutation (a caller catching the error keeps its old config)
        ds_bs = getattr(dataset, "batch_size", None)
        if batch_size is not None and ds_bs is not None \
                and hasattr(dataset, "eval_batch_fn_on") \
                and batch_size != ds_bs:
            raise ValueError(
                f"device-cached validation runs at the dataset's own "
                f"batch_size={ds_bs}; got conflicting batch_size="
                f"{batch_size} (omit it or rebuild the dataset)")
        self.validation_trigger = trigger
        self.validation_dataset = dataset
        self.validation_methods = list(methods)
        self._val_batch_size = batch_size or self.batch_size
        self._dc_eval = None  # new dataset: drop the old compiled slot
        return self

    def set_checkpoint(self, path: str, trigger: Trigger) -> "Optimizer":
        from bigdl_tpu.utils import file_io
        file_io.makedirs(path)
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger
        return self

    def overwrite_checkpoint(self) -> "Optimizer":
        self.is_overwrite = True
        return self

    def set_train_summary(self, summary) -> "Optimizer":
        self.train_summary = summary
        return self

    def set_val_summary(self, summary) -> "Optimizer":
        self.validation_summary = summary
        return self

    def set_model(self, new_model: Module) -> "Optimizer":
        """Swap the model before optimize() (Optimizer.scala:230)."""
        self.model = new_model
        # the device-cached validation slot closed over the OLD model's
        # forward at trace time — drop it or validation would silently
        # score the previous architecture
        self._dc_eval = None
        return self

    def set_state(self, state: Dict[str, Any]) -> "Optimizer":
        """Seed the driver's optimization state — epoch/neval counters
        etc. (Optimizer.scala:240 setState). Counter keys also reach
        the OptimMethod's state so epoch/iteration-driven lr schedules
        start from the seeded position, not epoch 1."""
        self.driver_state.update(dict(state))
        for k in ("epoch", "neval"):
            if k in state:
                self.optim_method.state[k] = state[k]
        return self

    def set_constant_gradient_clipping(self, min_value: float,
                                       max_value: float) -> "Optimizer":
        """Clip every gradient element into [min, max]
        (Optimizer.scala setConstantGradientClipping)."""
        if float(min_value) > float(max_value):
            raise ValueError(
                f"constant gradient clipping needs min <= max, got "
                f"[{min_value}, {max_value}] (jnp.clip would silently "
                "collapse every gradient to max)")
        self._gradient_clip = ("constant", float(min_value),
                               float(max_value))
        return self

    def set_gradient_clipping_by_l2_norm(self,
                                         clip_norm: float) -> "Optimizer":
        """Scale the aggregated gradients so their GLOBAL L2 norm never
        exceeds ``clip_norm`` (Optimizer.scala
        setGradientClippingByl2Norm) — the classic stabilizer for RNN
        recipes at aggressive learning rates."""
        self._gradient_clip = ("l2norm", float(clip_norm))
        return self

    def disable_gradient_clipping(self) -> "Optimizer":
        """Optimizer.scala disableGradientClipping."""
        self._gradient_clip = None
        return self

    def set_preflight_spec(self, input_spec) -> "Optimizer":
        """Opt-in pre-flight: before any compilation, ``optimize()``
        shape/dtype-checks the model against ``input_spec`` (see
        ``analysis.spec``; strings/None dims are symbolic) under
        ``jax.eval_shape`` and rejects a mis-wired model with a
        layer-path diagnostic instead of a deep XLA trace after a
        30-second compile. Pass None to disable."""
        self._preflight_spec = input_spec
        return self

    def set_drop_module_property(self, drop_percentage: float,
                                 max_drop_percentage: float,
                                 batchsize: int = 100,
                                 warmup_iteration: int = 200) -> "Optimizer":
        """Straggler dropping (Optimizer.scala:276). A synchronous TPU pod
        has no stragglers; accepted for recipe compatibility, does nothing."""
        self._drop_percentage = drop_percentage
        return self

    # -- sharding helpers --------------------------------------------------
    def _multiprocess(self) -> bool:
        """True when the mesh spans more than this process's devices —
        the multi-host regime the reference reached through Spark
        executors (Engine.scala:93-106); arrays must then be assembled
        from per-process local data."""
        return self.mesh is not None and jax.process_count() > 1

    def _data_parallel(self) -> bool:
        """True when the mesh actually splits the batch: a data axis of
        size > 1 (a size-1 axis — what the recipe's mesh builder emits
        when TP/PP consume every device — is the replicated regime)."""
        return self.mesh.shape.get(self.data_axis, 1) > 1

    def _batch_sharding(self):
        """Batch layout on the mesh: sharded over the data axis when it
        really splits, else replicated (pure TP/PP meshes)."""
        spec = jax.sharding.PartitionSpec(self.data_axis) \
            if self._data_parallel() else jax.sharding.PartitionSpec()
        return jax.sharding.NamedSharding(self.mesh, spec)

    def _put_batch(self, arr):
        from bigdl_tpu.dataset.sample import HostBatchedCOO
        if isinstance(arr, HostBatchedCOO):
            # SparseMiniBatch feed (MiniBatch.scala:587): transfer the
            # static-shape COO leaves like any dense batch (batch-dim
            # sharded) and rebuild the jit-compatible BCOO pytree
            if self._multiprocess() and not arr.fixed_nnz:
                raise ValueError(
                    "multi-host sparse batches must pad nnz to a FIXED "
                    "length (SampleToMiniBatch(feature_padding="
                    "PaddingParam(fixed_length=...))): each process "
                    "pads to its own batch max otherwise, and differing "
                    "static shapes desynchronize the SPMD programs")
            vals = self._put_batch(arr.values)
            idx = self._put_batch(arr.indices)
            return arr.to_bcoo(indices=idx, values=vals)
        if self.mesh is not None:
            sh = self._batch_sharding()
            if self._multiprocess() and not self._data_parallel():
                # pure TP/PP mesh (no data axis): the batch is
                # REPLICATED and every process must feed the identical
                # rows — cross-process model collectives then see one
                # consistent batch (megatron's broadcast-input regime)
                from bigdl_tpu.parallel.tp import put_global
                return put_global(np.asarray(arr), sh)
            if self._multiprocess():
                # each process contributes ITS batch rows; the global
                # batch is their concatenation in process order (the
                # role Spark partition locality played). Every process
                # must feed the same row count every step — a ragged
                # final batch would change the global shape mid-run (or
                # desynchronize iteration counts and deadlock the
                # collective), so fail fast instead.
                a = np.asarray(arr)
                expect = self._mp_batch_rows.get(self._stream)
                if expect is None:
                    self._mp_batch_rows[self._stream] = a.shape[0]
                elif a.shape[0] != expect:
                    raise ValueError(
                        f"multi-host {self._stream} batch changed size "
                        f"{expect} -> {a.shape[0]}: local datasets must "
                        "yield equal fixed-size batches per stream (drop "
                        "the remainder or pad the final batch)")
                gshape = (a.shape[0] * jax.process_count(),) + a.shape[1:]
                return jax.make_array_from_process_local_data(sh, a,
                                                              gshape)
            return jax.device_put(jnp.asarray(arr), sh)
        return jnp.asarray(arr)

    def _put_replicated(self, tree):
        if self.mesh is not None:
            sh = jax.sharding.NamedSharding(self.mesh,
                                            jax.sharding.PartitionSpec())
            if self._multiprocess():
                # every process holds the full value (init is
                # seed-identical); put_global assembles the global array
                from bigdl_tpu.parallel.tp import put_global
                return jax.tree.map(lambda a: put_global(a, sh), tree)
            return jax.device_put(tree, sh)
        return tree

    def _put_params(self, tree):
        """Params: TP/EP-sharded when rules are given, else replicated."""
        if self.mesh is not None and self.sharding_rules is not None:
            from bigdl_tpu.parallel.tp import shard_params, validate_rules
            problems = validate_rules(tree, self.mesh, self.sharding_rules)
            if problems:
                raise ValueError("bad sharding rules:\n" +
                                 "\n".join(problems))
            return shard_params(tree, self.mesh, self.sharding_rules)
        return self._put_replicated(tree)

    def _put_opt_state(self, tree):
        """Optimizer state (momentum/variance buffers mirror the params
        tree, so the TP rules match their paths too — re.search ignores the
        'momentum/' prefix). With zero1, moment buffers instead shard dim 0
        over the data axis (the reference's per-node owned shard running
        the OptimMethod, AllReduceParameter.scala:214-303 ≈ ZeRO-1)."""
        if self.mesh is None:
            return tree
        if self.zero1:
            from bigdl_tpu.parallel.tp import shard_opt_state_zero1
            return shard_opt_state_zero1(tree, self.mesh, self.data_axis)
        if self.sharding_rules is not None:
            from bigdl_tpu.parallel.tp import shard_params
            return shard_params(tree, self.mesh, self.sharding_rules)
        return self._put_replicated(tree)

    def _prep_io(self, batch: MiniBatch):
        inp = batch.get_input()
        tgt = batch.get_target()
        if isinstance(inp, (list, tuple)):
            from bigdl_tpu.utils.table import T as _T
            inp = _T(*[self._put_batch(x) for x in inp])
        else:
            inp = self._put_batch(inp)
        if isinstance(tgt, (list, tuple)):
            from bigdl_tpu.utils.table import T as _T
            tgt = _T(*[self._put_batch(x) for x in tgt])
        elif tgt is not None:
            tgt = self._put_batch(tgt)
        return inp, tgt

    # -- checkpointing (DistriOptimizer.checkpoint :433-463) ---------------
    def _checkpoint(self, params, opt_state, model_state):
        from bigdl_tpu.utils.serialization import save_checkpoint
        neval = self.driver_state["neval"]
        suffix = "" if self.is_overwrite else f".{neval}"
        path = os.path.join(self.checkpoint_path, f"checkpoint{suffix}")
        # single-writer in multi-host runs (the reference wrote once
        # from the driver, DistriOptimizer.scala:433-463): every process
        # participates in the collective host materialization inside
        # save_checkpoint, but only process 0 touches the (shared)
        # checkpoint storage — no N× duplicated IO
        writer = not self._multiprocess() or jax.process_index() == 0
        save_checkpoint(path, params=params, opt_state=opt_state,
                        model_state=model_state,
                        optim_host_state=self.optim_method.get_state(),
                        driver_state={k: v for k, v in
                                      self.driver_state.items()},
                        writer=writer)
        if writer:
            logger.info("checkpointed to %s", path)

    def _try_resume(self):
        from bigdl_tpu.utils.serialization import (find_latest_checkpoint,
                                                   load_checkpoint)
        if not self.checkpoint_path:
            return None
        latest = find_latest_checkpoint(self.checkpoint_path)
        if latest is None:
            return None
        logger.warning("retry: resuming from %s", latest)
        return load_checkpoint(latest)

    # -- validation (DistriOptimizer.scala:607-686) ------------------------
    def _validate(self, params, model_state, eval_step):
        self._stream = "validate"
        try:
            return self._validate_impl(params, model_state, eval_step)
        finally:
            self._stream = "train"

    def _validate_impl(self, params, model_state, eval_step):
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch
        ds = self.validation_dataset
        if hasattr(ds, "eval_batch_fn_on"):
            return self._validate_device_cached(params, model_state, ds)
        it = ds.data(train=False)
        results = None
        # Accept datasets of Samples or of MiniBatches
        batcher = SampleToMiniBatch(self._val_batch_size)
        peek = []
        for el in it:
            peek.append(el)
            break
        if not peek:
            return {}
        import itertools
        full_it = itertools.chain(peek, it)
        if isinstance(peek[0], MiniBatch):
            batches = full_it
        else:
            batches = batcher.apply(full_it)
        for b in batches:
            inp, tgt = self._prep_io(b)
            out = eval_step(params, model_state, inp)
            # multi-host: out/tgt span non-addressable devices; each
            # process scores ITS rows (the reference aggregated
            # per-executor ValidationResults the same way — here the
            # local shard IS this process's data)
            out_np, tgt_np = _local_rows(out), _local_rows(tgt)
            batch_res = [m(out_np, tgt_np)
                         for m in self.validation_methods]
            if results is None:
                results = batch_res
            else:
                results = [r + br for r, br in zip(results, batch_res)]
        if self._multiprocess():
            # reduce ValidationResults across processes (the reference
            # reduce(+)s per-executor results, DistriOptimizer.scala:607)
            results = [_allreduce_result(r) for r in results]
        return self._score_summary(results)

    def _validate_device_cached(self, params, model_state, ds):
        """Trigger-driven validation straight off the HBM cache
        (DeviceCachedArrayDataSet passed to set_validation): one jitted
        sample+forward per batch, zero per-trigger host feed — the
        device-resident form of validation riding the same cached
        distributed dataset as training (DistriOptimizer.scala:607-686).

        Intentionally NOT delegated to Predictor._device_cached_sweep:
        validation fires every trigger, so the compiled sweep must be
        CACHED across calls (the single-slot ``_dc_eval`` below) —
        keep the divisibility guard and trim rules in lockstep with
        predictor.py's one-shot sweep when changing either.
        """
        fn = self._dc_eval[1] if (self._dc_eval is not None
                                  and self._dc_eval[0] is ds) else None
        if fn is None:
            ev_sh = self._batch_sharding() if self.mesh is not None \
                else None

            def _ev(p, m, start, images, labels):
                x, y = ds.eval_batch_fn_on(images, labels, start)
                out, _ = self.model.apply(p, m, x, training=False)
                return out, y

            fn = jax.jit(_ev, out_shardings=(ev_sh, ev_sh))
            self._dc_eval = (ds, fn)
        n, b = ds.size(), ds.batch_size
        if self._multiprocess() and n % b:
            raise ValueError(
                "device-cached multi-host validation needs batch_size to "
                "divide the dataset (a wrapped final batch cannot be "
                "trimmed consistently across processes)")
        results = None
        for start in range(0, n, b):
            out, y = fn(params, model_state, jnp.int32(start),
                        ds.images, ds.labels)
            out_np, tgt_np = _local_rows(out), _local_rows(y)
            valid = min(b, n - start)
            if valid < b:  # eval_batch_fn wraps modulo n; trim the tail
                out_np, tgt_np = out_np[:valid], tgt_np[:valid]
            batch_res = [m(out_np, tgt_np)
                         for m in self.validation_methods]
            results = batch_res if results is None else \
                [r + br for r, br in zip(results, batch_res)]
        if self._multiprocess():
            results = [_allreduce_result(r) for r in results]
        return self._score_summary(results)

    def _score_summary(self, results):
        summary = {}
        for m, r in zip(self.validation_methods, results):
            value, _ = r.result()
            # unique key per method so duplicates (e.g. two Loss instances)
            # don't overwrite each other — first key must stay the FIRST
            # method (driver_state["score"] reads it)
            key, k = m.name, 2
            while key in summary:
                key = f"{m.name}-{k}"
                k += 1
            summary[key] = value
            logger.info("validation %s: %s", key, r)
        return summary

    # -- the loop (optimize(), DistriOptimizer.scala:154-421) --------------
    def optimize(self) -> Module:
        if not Engine.is_initialized():
            Engine.init()
        if self._preflight_spec is not None:
            # pre-flight OUTSIDE the retry loop: a structurally broken
            # model fails identically every attempt, so reject it once,
            # with a layer-path diagnostic, before any init/compile work
            self.model.check(self._preflight_spec, training=True)
        retries = 0
        while True:
            try:
                return self._optimize_impl()
            except (KeyboardInterrupt,):
                raise
            except Exception as e:  # retry-from-checkpoint loop
                retries += 1
                if retries > self.retry_times or self.checkpoint_path is None:
                    raise
                logger.exception("training failed (%s); retry %d/%d",
                                 e, retries, self.retry_times)
                time.sleep(self.retry_interval_s)

    def _optimize_impl(self) -> Module:
        model = self.model
        model.training()
        model.ensure_initialized()
        params = model.get_parameters()
        model_state = model.get_state()
        opt_state = self.optim_method.init_state(params)

        resumed = self._try_resume()
        if resumed is not None:
            params = resumed["params"]
            opt_state = resumed["opt_state"]
            model_state = resumed["model_state"]
            self.optim_method.load_state(resumed["optim_host_state"])
            self.driver_state.update(resumed["driver_state"])
        # epoch/iteration-driven lr schedules read the OptimMethod's
        # state: sync the driver counters in (covers set_state called
        # before set_optim_method, and keeps both views consistent)
        for k in ("epoch", "neval"):
            if k in self.driver_state:
                self.optim_method.state[k] = self.driver_state[k]

        params = self._put_params(params)
        opt_state = self._put_opt_state(opt_state)
        model_state = self._put_replicated(model_state)

        step = build_train_step(model, self.criterion, self.optim_method,
                                gradient_clip=self._gradient_clip)
        ev_sh = self._batch_sharding() if self.mesh is not None else None
        eval_step = build_eval_step(model, ev_sh)

        ds_size = self.dataset.size()
        state = self.driver_state
        # Device-cached feed (DeviceCachedArrayDataSet): the batch is
        # sampled + augmented INSIDE the jitted step — zero per-step
        # host->device traffic (the HBM form of the reference's decoded
        # executor cache, DataSet.scala CachedDistriDataSet:240).
        rotating = getattr(self.dataset, "rotating", False)
        device_feed = rotating or hasattr(self.dataset, "batch_fn")
        if rotating:
            # rotating HBM shard cache (RotatingDeviceDataSet): the slot
            # arrays MUST be step arguments — a closure would bake them
            # in as compile-time constants and train on the first shard
            # forever; as arguments, each rotation is a plain rebind of
            # the one compiled step
            ds = self.dataset
            tmpl = ds.template

            def _fused_rot(p, o, m, key, lr, ep, pos, images, labels):
                kb, kr = jax.random.split(key)
                x, y = tmpl.batch_fn_on(images, labels, kb,
                                        epoch=ep, pos=pos)
                return step(p, o, m, kr, lr, x, y)

            fused_step = jax.jit(_fused_rot, donate_argnums=(0, 1, 2))
            data_iter = None
        elif device_feed:
            ds = self.dataset
            # epoch-exact feed: the global iteration index drives a
            # per-epoch permutation inside batch_fn (DataSet.scala:240
            # shuffle semantics); datasets without sample_indices keep
            # the rng-only contract
            epoch_exact = hasattr(ds, "sample_indices")
            # on a mesh spanning processes the cache arrays are global
            # arrays with non-addressable shards — jit cannot close over
            # those; pass them as arguments (batch_fn_on) when available
            feed_by_arg = hasattr(ds, "batch_fn_on")

            if feed_by_arg:
                def _fused(p, o, m, key, lr, ep, pos, images, labels):
                    kb, kr = jax.random.split(key)
                    x, y = ds.batch_fn_on(images, labels, kb,
                                          epoch=ep, pos=pos) \
                        if epoch_exact else \
                        ds.batch_fn_on(images, labels, kb)
                    return step(p, o, m, kr, lr, x, y)
            else:
                def _fused(p, o, m, key, lr, ep, pos):
                    kb, kr = jax.random.split(key)
                    x, y = ds.batch_fn(kb, epoch=ep, pos=pos) \
                        if epoch_exact else ds.batch_fn(kb)
                    return step(p, o, m, kr, lr, x, y)

            # donate like build_train_step does — inner-jit donation is
            # ignored when traced inside an outer jit
            fused_step = jax.jit(_fused, donate_argnums=(0, 1, 2))
            data_iter = None
        else:
            data_iter = self.dataset.data(train=True)
        end_when = self.end_when
        if end_when is None:
            from bigdl_tpu.optim.trigger import max_epoch
            end_when = max_epoch(10)

        wall_start = time.time()
        while not end_when(state):
            t0 = time.time()
            if rotating:
                bsz = self.dataset.batch_size
                visit, sp = self.dataset.shard_cursor(state["neval"])
                step_args = (jnp.int32(visit), jnp.int32(sp),
                             self.dataset.images, self.dataset.labels)
                run_step = fused_step
            elif device_feed:
                bsz = self.dataset.batch_size
                # neval starts at 1 (reference convention); the sample
                # stream is 0-based so epoch boundaries line up with
                # recordsProcessedThisEpoch rollover. The (epoch, pos)
                # cursor is decomposed HERE with exact Python integers,
                # so no device-int overflow however long the run.
                e0, p0 = divmod((state["neval"] - 1) * bsz, ds_size)
                step_args = (jnp.int32(e0), jnp.int32(p0))
                if feed_by_arg:
                    step_args += (self.dataset.images,
                                  self.dataset.labels)
                run_step = fused_step
            else:
                batch = next(data_iter)
                if not isinstance(batch, MiniBatch):
                    raise ValueError(
                        "dataset must yield MiniBatch; add SampleToMiniBatch")
                inp, tgt = self._prep_io(batch)
                # device_put above only DISPATCHED the transfer; without
                # this barrier the copy time would silently migrate into
                # t_compute and the data-vs-compute attribution would lie
                jax.block_until_ready((inp, tgt))
                bsz = batch.size()
                step_args = (inp, tgt)
                run_step = step
            t_data = time.time() - t0
            # trace carries the EXACT t_data the Metrics dump reports,
            # so diagnose's phase attribution and Metrics.summary()
            # agree to the digit
            telemetry.record("optimizer/data_wait", t_data,
                             step=state["neval"])

            lr = self.optim_method.update_hyper_parameter()
            rng = RandomGenerator.next_key()
            t1 = time.time()
            params, opt_state, model_state, loss = run_step(
                params, opt_state, model_state, rng, lr, *step_args)
            # fetching the loss scalar only gates on the loss VALUE; the
            # param/optimizer updates it does not depend on may still be
            # in flight, so close the timing window on the full outputs
            jax.block_until_ready((params, opt_state, model_state))
            loss_f = _to_scalar(loss)
            t_compute = time.time() - t1
            telemetry.record("optimizer/compute", t_compute,
                             step=state["neval"])
            _STEP_COUNT.inc()
            _RECORD_COUNT.inc(bsz)
            if rotating:
                # loss fetch above completed the step; stream the next
                # shard piece now (alternation rule) and rotate slots at
                # shard boundaries
                self.dataset.after_step(state["neval"])

            state["neval"] += 1
            self.optim_method.state["neval"] = state["neval"]
            state["recordsProcessedThisEpoch"] += bsz
            state["Loss"] = loss_f
            state["LearningRate"] = lr
            state["Throughput"] = bsz / max(1e-9, t_data + t_compute)
            self.metrics.add("data time", t_data)
            self.metrics.add("computing time", t_compute)
            logger.info(
                "Epoch %d iter %d: loss %.4f lr %.5f throughput %.1f rec/s",
                state["epoch"], state["neval"] - 1, loss_f, lr,
                state["Throughput"])

            if self.train_summary is not None:
                self.train_summary.add_scalar("Loss", loss_f, state["neval"])
                self.train_summary.add_scalar("LearningRate", lr,
                                              state["neval"])
                self.train_summary.add_scalar("Throughput",
                                              state["Throughput"],
                                              state["neval"])
                # per-parameter histograms, opt-in via trigger
                # (TrainSummary.scala:64; DistriOptimizer.scala:464-498)
                get_trig = getattr(self.train_summary,
                                   "get_summary_trigger", None)
                ptrig = get_trig("Parameters") if get_trig else None
                if ptrig is not None and ptrig(state):
                    flat, _ = jax.tree_util.tree_flatten_with_path(params)
                    for path, leaf in flat:
                        tag = "/".join(
                            str(getattr(k, "key", getattr(k, "idx", k)))
                            for k in path)
                        self.train_summary.add_histogram(
                            tag, np.asarray(leaf), state["neval"])

            # epoch rollover (DistriOptimizer.scala:368-380). Carry the
            # overshoot: when batch_size does not divide ds_size a batch
            # straddles the epoch boundary, and resetting to 0 would make
            # the driver's epoch drift from the sample stream's true
            # permutation epochs (epoch-driven lr schedules / triggers
            # would fire progressively late)
            while state["recordsProcessedThisEpoch"] >= ds_size:
                # while, not if: one batch can span several epochs when
                # batch_size > ds_size
                state["epoch"] += 1
                self.optim_method.state["epoch"] = state["epoch"]
                state["recordsProcessedThisEpoch"] -= ds_size
                if not device_feed and not getattr(
                        self.dataset, "continuous_stream", False):
                    # a restartable iterator begins a FRESH permutation,
                    # so the overshoot carry would skip its tail — reset
                    # to 0; continuous streams (device feed, the
                    # ImageFolder _IndexStream) keep the carry, which
                    # tracks their true permutation boundary exactly
                    state["recordsProcessedThisEpoch"] = 0
                    self.dataset.shuffle()
                    data_iter = self.dataset.data(train=True)

            # validation / checkpoint triggers (:382-411)
            if (self.validation_trigger is not None
                    and self.validation_trigger(state)):
                with telemetry.span("optimizer/validate",
                                    step=state["neval"]):
                    scores = self._validate(params, model_state,
                                            eval_step)
                if scores:
                    # The first method's result drives maxScore/Plateau —
                    # a max() across heterogeneous methods (e.g. Top1 vs
                    # Loss) would act on the wrong number
                    # (DistriOptimizer.scala:382-397 uses head).
                    state["score"] = next(iter(scores.values()))
                    sched = getattr(self.optim_method,
                                    "learning_rate_schedule", None)
                    if sched is not None and hasattr(sched, "record_metric"):
                        sched.record_metric(state["score"])
                    if self.validation_summary is not None:
                        for k, v in scores.items():
                            self.validation_summary.add_scalar(
                                k, v, state["neval"])
            if (self.checkpoint_trigger is not None
                    and self.checkpoint_trigger(state)):
                with telemetry.span("optimizer/checkpoint",
                                    step=state["neval"]):
                    self._checkpoint(params, opt_state, model_state)

        logger.info("training done in %.1fs; %s", time.time() - wall_start,
                    self.metrics.summary())
        # write trained params back to the stateful module (multi-host
        # safe: ZeRO-1 can leave updated params data-sharded, and a
        # spanning shard is not plain-readable — host_value reshards)
        from bigdl_tpu.utils.serialization import host_value
        model.set_parameters(jax.tree.map(host_value, params))
        model.set_state(jax.tree.map(host_value, model_state))
        return model


class LocalOptimizer(Optimizer):
    """Single-process training on whatever single device jax default is
    (optim/LocalOptimizer.scala:41)."""

    def __init__(self, model, dataset, criterion, batch_size: int = 32):
        super().__init__(model, dataset, criterion, batch_size, mesh=None)


class DistriOptimizer(Optimizer):
    """Synchronous data-parallel training over the Engine mesh
    (optim/DistriOptimizer.scala:728)."""

    def __init__(self, model, dataset, criterion, batch_size: int = 32,
                 mesh: Optional[jax.sharding.Mesh] = None):
        super().__init__(model, dataset, criterion, batch_size,
                         mesh=mesh or Engine.mesh())
