"""Ragged decode attention — read only ``lengths[i]`` valid KV per slot.

The decode engine's per-step cost story: every slot's query attends a
*preallocated* cache row padded to the attend-length bucket, so the
einsum path pays O(slots × bucket) work and bytes no matter how short
the live sequences are. At high occupancy with mixed lengths that is
the decode tokens/sec ceiling. This kernel walks each slot's KV in
``block_k`` tiles under a **dynamic** ``fori_loop`` bound
``cdiv(lengths[i], block_k)`` — the classic online-softmax rescaling
form — so a slot 17 tokens into a 512 bucket reads one tile, not 512
rows. The host ``lengths`` vector (``KVCache.lengths``, the same array
the engine already threads as ``positions``) rides into SMEM and is
the ONLY ragged input: block shapes stay static, so kernel variants
never multiply the ≤ 2-programs-per-bucket bound
(:mod:`bigdl_tpu.generation.engine`).

One token per slot (decode's shape), grid ``(slots, heads)``; used
through :func:`bigdl_tpu.kernels.decode_attention`, which owns
eligibility and the jnp fallback.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bigdl_tpu.kernels.common import fit_block

__all__ = ["ragged_decode_attention"]

_NEG_INF = float("-inf")


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *,
                   block_k: int, sm_scale: float):
    slot = pl.program_id(0)
    n = len_ref[slot]                                   # valid KV rows
    q = q_ref[0, 0].reshape(1, -1).astype(jnp.float32) * sm_scale

    def body(i, carry):
        m, l, acc = carry
        kb = k_ref[0, 0, pl.ds(i * block_k, block_k), :]
        s = jax.lax.dot_general(q, kb.astype(jnp.float32),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        col = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.where(col < n, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # first tile: m = -inf, m_new finite (col 0 < n always) so
        # alpha underflows to an exact 0 and the zero-initialized
        # carry drops out; every later tile holds >= 1 valid column
        # (the loop bound is cdiv(n, block_k)), keeping m_new finite
        alpha = jnp.exp(m - m_new)
        p = jnp.where(col < n, jnp.exp(s - m_new), 0.0)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        vb = v_ref[0, 0, pl.ds(i * block_k, block_k), :]
        acc = acc * alpha + jax.lax.dot_general(
            p, vb.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    d = q.shape[-1]
    m0 = jnp.full((1, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((1, 1), jnp.float32)
    acc0 = jnp.zeros((1, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, pl.cdiv(n, block_k), body,
                                  (m0, l0, acc0))
    o_ref[0, 0] = (acc / l)[0].astype(o_ref.dtype)


def ragged_decode_attention(q, k, v, lengths, *, sm_scale: float = None,
                            block_k: int = 128,
                            interpret: bool = False):
    """One decode step of attention over ragged KV: ``q`` is
    ``[slots, H, D]`` (the step's single token per slot), ``k``/``v``
    are ``[slots, H, T, D]`` cache slices, ``lengths`` the host int32
    ``[slots]`` of valid rows per slot (clamped into ``[1, T]`` — a
    free slot reads one garbage row whose output is never consumed,
    matching the engine's inactive-slot contract). Returns
    ``[slots, H, D]``."""
    from jax.experimental.pallas import tpu as pltpu

    slots, h, t, d = k.shape
    if q.shape != (slots, h, d):
        raise ValueError(f"q {q.shape} does not match cache "
                         f"[{slots},{h},{t},{d}]")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    block_k = fit_block(t, block_k)
    lengths = jnp.clip(lengths.astype(jnp.int32), 1, t)
    kernel = functools.partial(_decode_kernel, block_k=block_k,
                               sm_scale=float(sm_scale))
    return pl.pallas_call(
        kernel,
        grid=(slots, h),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, d), lambda s, h_: (s, h_, 0)),
            pl.BlockSpec((1, 1, t, d), lambda s, h_: (s, h_, 0, 0)),
            pl.BlockSpec((1, 1, t, d), lambda s, h_: (s, h_, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda s, h_: (s, h_, 0)),
        out_shape=jax.ShapeDtypeStruct((slots, h, d), q.dtype),
        interpret=interpret,
    )(lengths, q, k, v)
