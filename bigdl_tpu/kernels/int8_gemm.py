"""Fused dequant-int8 GEMM — the BigQuant story's serving kernel.

The reference's BigQuant ships hand-written SIMD int8 GEMM (C++ via
JNI — SURVEY.md §1 L0). The TPU analogue keeps the int8 multiply on
the MXU with int32 accumulation across K tiles in VMEM scratch and
fuses the fp32 dequant epilogue (``acc · x_scale · w_scale``) into the
same kernel — the int32 accumulator never round-trips HBM. Scales come
from the ONE max-abs rule (:func:`bigdl_tpu.ops.quant.scale_from_amax`):
dynamic per-row, or the calibrated per-tensor scales PR 9's
``precision/calibrate.py`` certifies.

**Bitwise contract:** integer accumulation is exact under K-splitting,
and the epilogue multiplies in the same order as the reference
(``ops.quant.quantized_linear``), so the kernel is *bit-identical* to
dequantize-then-matmul. The bias add deliberately lives in the
dispatch layer (one jnp add shared by both paths): fused into the
kernel, XLA contracts ``mul·mul + bias`` into an FMA and the result
drifts one ulp from the reference — measured, which is why the
kernel's ``with_bias`` epilogue exists for full-fusion callers but the
dispatched path adds bias outside (docs/kernels.md "Equivalence
contract").

Used through :func:`bigdl_tpu.kernels.int8_matmul`; the legacy import
site ``bigdl_tpu.ops.pallas_kernels`` re-exports from here.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bigdl_tpu.kernels.common import fit_block, tpu_compiler_params

__all__ = ["pallas_quantized_matmul"]


def _qmm_kernel(x_ref, w_ref, xs_ref, ws_ref, b_ref, o_ref, acc_ref, *,
                k_steps: int, with_bias: bool):
    """One (bm, bn) output tile; K is the innermost ("arbitrary") grid
    dim.

    x_ref: (bm, bk) int8 activations | w_ref: (bn, bk) int8 weights
    xs_ref: (bm, 1) f32 row scales   | ws_ref: (1, bn) f32 channel scales
    acc_ref: (bm, bn) int32 scratch accumulator
    """
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _epilogue():
        out = acc_ref[...].astype(jnp.float32) * xs_ref[...] * ws_ref[...]
        if with_bias:
            out = out + b_ref[...]
        o_ref[...] = out


@functools.partial(jax.jit, static_argnames=("bm", "bn", "bk",
                                             "interpret"))
def pallas_quantized_matmul(x_q, w_q, x_scale, w_scale, bias=None, *,
                            bm: int = 256, bn: int = 256, bk: int = 512,
                            interpret: bool = False):
    """Fused int8 GEMM + dequant: ``(x_q [M,K] i8) @ (w_q [N,K] i8)^T``
    rescaled by per-row ``x_scale`` and per-channel ``w_scale``
    (module docstring has the memory story and bitwise contract).
    Block sizes shrink to the largest divisor of each dim, so any
    shape tiles exactly; ``bias=None`` is the bit-identical dispatched
    form (bias added by the caller), a non-None ``bias`` fuses the add
    at one-ulp FMA tolerance."""
    from jax.experimental.pallas import tpu as pltpu

    m, k = x_q.shape
    n = w_q.shape[0]
    bm, bn, bk = fit_block(m, bm), fit_block(n, bn), fit_block(k, bk)
    k_steps = k // bk
    with_bias = bias is not None
    xs = x_scale.reshape(m, 1).astype(jnp.float32)
    ws = w_scale.reshape(1, n).astype(jnp.float32)
    b = (bias.reshape(1, n).astype(jnp.float32) if with_bias
         else jnp.zeros((1, n), jnp.float32))

    grid = (m // bm, n // bn, k_steps)
    kernel = functools.partial(_qmm_kernel, k_steps=k_steps,
                               with_bias=with_bias)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bn, bk), lambda i, j, kk: (j, kk)),
            pl.BlockSpec((bm, 1), lambda i, j, kk: (i, 0)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.int32)],
        compiler_params=tpu_compiler_params(
            ("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x_q, w_q, xs, ws, b)
