"""Fused flash attention for training — pallas, segment-mask aware.

The [S, S] score matrix never exists in memory: the grid tiles the
query axis and each program holds one ``[block_q, S]`` score strip in
VMEM, computes a numerically-stable softmax over the full key axis and
contracts straight into the ``[block_q, D]`` output — O(S·block_q)
live bytes instead of O(S²) (the memory property that lets S=32K run
where the einsum path dies; nn/attention's ``_FLASH_SCORE_BYTES``
measurement note). The backward recomputes the strip from the saved
log-sum-exp and accumulates dK/dV across query tiles in VMEM scratch —
no residual score matrix either.

**Why full-row reductions instead of blockwise rescaling:** the
classic online-softmax rescales the running accumulator by
``exp(m_old - m_new)`` at every key block, which makes the result
depend on where block boundaries fall. Packed training slabs
(``bigdl_tpu.datapipe.packing``) put documents at arbitrary row
offsets, and the datapipe's contract is that a packed forward is
**bit-exact per token** against each document run alone — a guarantee
blockwise rescaling breaks (the rescale rounds differently per
offset). Reducing each query's full key row at once keeps masked
positions as *exact zeros* in the sum, which commutes with document
offset, so the packed-slab bitwise contract survives the kernel
(tests/test_kernels.py asserts it per token). The decode kernel
(:mod:`bigdl_tpu.kernels.decode_attention`), whose win is *skipping*
tail key blocks, uses the true online rescaling form — its contract is
tolerance, not bitwise.

Masking: ``causal`` and/or ``segment_ids`` (``[B, S]`` int32; queries
attend only same-segment keys — the packed-slab mask). Masked scores
are ``-inf`` so they vanish exactly from max/sum; a fully-masked query
row yields 0 output, not NaN.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bigdl_tpu.kernels.common import fit_block, tpu_compiler_params

__all__ = ["flash_attention", "blockwise_flash_attention", "fit_block"]

_NEG_INF = float("-inf")


def _mask_for(i, block_q, s, causal, seg_q, seg_k):
    """The boolean keep-mask for query tile ``i``: ``[block_q, s]``,
    or None when nothing masks."""
    mask = None
    if causal:
        rows = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, s), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, s), 1)
        mask = cols <= rows
    if seg_q is not None:
        seg = seg_q[:, None] == seg_k[None, :]
        mask = seg if mask is None else mask & seg
    return mask


def _fwd_kernel(*refs, causal: bool, block_q: int, sm_scale: float,
                segmented: bool):
    if segmented:
        q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref, lse_ref = refs
        seg_q, seg_k = sq_ref[0], sk_ref[0]
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        seg_q = seg_k = None
    i = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale          # [bq, D]
    k = k_ref[0, 0]                                         # [S, D]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    mask = _mask_for(i, block_q, s.shape[-1], causal, seg_q, seg_k)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                  # [bq, 1]
    # exp(-inf - -inf) = nan on fully-masked rows; the where() zeroes
    # every masked lane EXACTLY, which is what keeps packed slabs
    # bit-faithful (module docstring)
    p = jnp.exp(s - m)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)                  # [bq, 1]
    acc = jax.lax.dot_general(p, v_ref[0, 0].astype(jnp.float32),
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    o_ref[0, 0] = jnp.where(l > 0, acc / l, 0.0).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.where(l[:, 0] > 0, m[:, 0] + jnp.log(l[:, 0]),
                              _NEG_INF)


def _bwd_kernel(*refs, causal: bool, block_q: int, sm_scale: float,
                segmented: bool, q_tiles: int):
    if segmented:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, sq_ref, sk_ref,
         dq_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
        seg_q, seg_k = sq_ref[0], sk_ref[0]
    else:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
         dq_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
        seg_q = seg_k = None
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q = q_ref[0, 0].astype(jnp.float32)                     # [bq, D]
    k = k_ref[0, 0].astype(jnp.float32)                     # [S, D]
    v = v_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)                   # [bq, D]
    o = o_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]                                     # [bq]
    s = jax.lax.dot_general(q * sm_scale, k,
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    mask = _mask_for(i, block_q, s.shape[-1], causal, seg_q, seg_k)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    # softmax weights straight from the saved log-sum-exp; masked (and
    # fully-masked: -inf - -inf = nan) lanes zeroed exactly
    p = jnp.exp(s - lse[:, None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    p = jnp.where(jnp.isfinite(lse)[:, None], p, 0.0)
    dv_acc[...] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    delta = jnp.sum(do * o, axis=-1, keepdims=True)         # [bq, 1]
    ds = p * (dp - delta) * sm_scale                        # [bq, S]
    dq_ref[0, 0] = jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_acc[...] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)

    @pl.when(i == q_tiles - 1)
    def _write():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _fwd_call(q, k, v, segment_ids, causal, sm_scale, block_q,
              interpret):
    b, h, s, d = q.shape
    grid = (b, h, s // block_q)
    segmented = segment_ids is not None
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i: (b_, h_, i, 0)),
        pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
        pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
    ]
    args = [q, k, v]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, block_q), lambda b_, h_, i: (b_, i)),
            pl.BlockSpec((1, s), lambda b_, h_, i: (b_, 0)),
        ]
        args += [segment_ids.astype(jnp.int32),
                 segment_ids.astype(jnp.int32)]
    kernel = functools.partial(_fwd_kernel, causal=causal,
                               block_q=block_q, sm_scale=sm_scale,
                               segmented=segmented)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b_, h_, i: (b_, h_, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(*args)


def _bwd_call(q, k, v, o, do, lse, segment_ids, causal, sm_scale,
              block_q, interpret):
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    q_tiles = s // block_q
    grid = (b, h, q_tiles)
    segmented = segment_ids is not None
    tile = pl.BlockSpec((1, 1, block_q, d),
                        lambda b_, h_, i: (b_, h_, i, 0))
    full = pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0))
    in_specs = [tile, full, full, tile, tile,
                pl.BlockSpec((1, 1, block_q),
                             lambda b_, h_, i: (b_, h_, i))]
    args = [q, k, v, o, do, lse]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, block_q), lambda b_, h_, i: (b_, i)),
            pl.BlockSpec((1, s), lambda b_, h_, i: (b_, 0)),
        ]
        args += [segment_ids.astype(jnp.int32),
                 segment_ids.astype(jnp.int32)]
    kernel = functools.partial(_bwd_kernel, causal=causal,
                               block_q=block_q, sm_scale=sm_scale,
                               segmented=segmented, q_tiles=q_tiles)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[tile, full, full],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((s, d), jnp.float32),
                        pltpu.VMEM((s, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(*args)


def _compiler_params():
    """q tiles iterate innermost and carry the backward's dK/dV
    scratch, so that axis is "arbitrary" (sequential); batch and heads
    are parallel."""
    return tpu_compiler_params(("parallel", "parallel", "arbitrary"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, segment_ids, causal, sm_scale, block_q, interpret):
    out, _ = _fwd_call(q, k, v, segment_ids, causal, sm_scale, block_q,
                       interpret)
    return out


def _flash_fwd(q, k, v, segment_ids, causal, sm_scale, block_q,
               interpret):
    out, lse = _fwd_call(q, k, v, segment_ids, causal, sm_scale,
                         block_q, interpret)
    return out, (q, k, v, out, lse, segment_ids)


def _flash_bwd(causal, sm_scale, block_q, interpret, res, g):
    q, k, v, out, lse, segment_ids = res
    dq, dk, dv = _bwd_call(q, k, v, out, g, lse, segment_ids, causal,
                           sm_scale, block_q, interpret)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


# --------------------------------------------------------------------
# Blockwise long-context path: key axis tiled through VMEM.
#
# The full-row kernels above hold one [block_q, S] strip plus the whole
# K/V in VMEM — past ~12 MiB of working set (S≈24K at D=64 f32) Mosaic
# would OOM, so dispatch historically DECLINED and S=32K fell back to
# the O(S²) einsum. These kernels are the classic online-softmax
# blockwise form instead: the grid also tiles the KEY axis, one
# [block_q, block_k] score tile lives at a time, and the running
# (m, l, acc) state is rescaled by exp(m_old - m_new) per key tile in
# VMEM scratch. Working set is O(block_q·block_k + (block_q+block_k)·D)
# — independent of S — so S=128K runs fused.
#
# The rescaling makes results depend on where key-block boundaries
# fall, which breaks the packed-slab BITWISE contract the full-row
# kernels keep (module docstring) — so this path is tolerance-
# contract, reserved by dispatch for shapes the full-row kernels
# cannot hold, and never silently substituted under the budget.
# Causal masking skips fully-masked key tiles outright (the FLOP win
# that makes causal blockwise ~2x the dense form).

#: lane width of the (m, l) running-statistics scratch rows — the f32
#: min-tile lane count, stored broadcast so no width-1 lane slicing
#: ever reaches Mosaic
_STAT_LANES = 128


def _tile_mask(i, j, block_q, block_k, causal, seg_q, seg_k):
    """Keep-mask for score tile (query tile ``i``, key tile ``j``):
    ``[block_q, block_k]``, or None when nothing masks."""
    mask = None
    if causal:
        rows = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = cols <= rows
    if seg_q is not None:
        seg = seg_q[:, None] == seg_k[None, :]
        mask = seg if mask is None else mask & seg
    return mask


def _bw_fwd_kernel(*refs, causal: bool, block_q: int, block_k: int,
                   sm_scale: float, segmented: bool, k_tiles: int):
    if segmented:
        (q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref, lse_ref,
         m_acc, l_acc, acc) = refs
        seg_q, seg_k = sq_ref[0], sk_ref[0]
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs[:5]
        m_acc, l_acc, acc = refs[5:]
        seg_q = seg_k = None
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_acc[...] = jnp.full_like(m_acc, _NEG_INF)
        l_acc[...] = jnp.zeros_like(l_acc)
        acc[...] = jnp.zeros_like(acc)

    # causal: a key tile strictly right of the query tile's last row is
    # fully masked — skip its FLOPs and leave the carry untouched
    live = (j * block_k <= i * block_q + block_q - 1) if causal \
        else (j >= 0)

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale      # [bq, D]
        k = k_ref[0, 0]                                     # [bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        mask = _tile_mask(i, j, block_q, block_k, causal, seg_q, seg_k)
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        # scratch rows hold the stat broadcast across _STAT_LANES; a
        # lane-reduce recovers it without a width-1 lane slice
        m_old = jnp.max(m_acc[...], axis=-1, keepdims=True)  # [bq, 1]
        l_old = jnp.max(l_acc[...], axis=-1, keepdims=True)
        m_new = jnp.maximum(m_old, jnp.max(s, axis=-1, keepdims=True))
        # m_new = -inf only while EVERY lane so far is masked (any
        # unmasked lane is a finite dot product); exp guards below
        # keep those all-masked rows at exact (0, 0) carries, no NaN
        p = jnp.exp(s - m_new)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        p = jnp.where(jnp.isfinite(m_new), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m_old),
                          jnp.exp(m_old - m_new), 0.0)     # [bq, 1]
        l_new = l_old * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc[...] = acc[...] * alpha + jax.lax.dot_general(
            p, v_ref[0, 0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_acc[...] = jnp.broadcast_to(m_new, m_acc.shape)
        l_acc[...] = jnp.broadcast_to(l_new, l_acc.shape)

    @pl.when(j == k_tiles - 1)
    def _finalize():
        m = jnp.max(m_acc[...], axis=-1, keepdims=True)
        l = jnp.max(l_acc[...], axis=-1, keepdims=True)
        o_ref[0, 0] = jnp.where(l > 0, acc[...] / l, 0.0) \
            .astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.where(l[:, 0] > 0,
                                  m[:, 0] + jnp.log(l[:, 0]), _NEG_INF)


def _bw_fwd_call(q, k, v, segment_ids, causal, sm_scale, block_q,
                 block_k, interpret):
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    k_tiles = s // block_k
    grid = (b, h, s // block_q, k_tiles)
    segmented = segment_ids is not None
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d),
                     lambda b_, h_, i, j: (b_, h_, i, 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda b_, h_, i, j: (b_, h_, j, 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda b_, h_, i, j: (b_, h_, j, 0)),
    ]
    args = [q, k, v]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, block_q), lambda b_, h_, i, j: (b_, i)),
            pl.BlockSpec((1, block_k), lambda b_, h_, i, j: (b_, j)),
        ]
        args += [segment_ids.astype(jnp.int32),
                 segment_ids.astype(jnp.int32)]
    kernel = functools.partial(_bw_fwd_kernel, causal=causal,
                               block_q=block_q, block_k=block_k,
                               sm_scale=sm_scale, segmented=segmented,
                               k_tiles=k_tiles)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q),
                         lambda b_, h_, i, j: (b_, h_, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),
                        pltpu.VMEM((block_q, _STAT_LANES), jnp.float32),
                        pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_bw_compiler_params(),
        interpret=interpret,
    )(*args)


def _bw_dq_kernel(*refs, causal: bool, block_q: int, block_k: int,
                  sm_scale: float, segmented: bool, k_tiles: int):
    if segmented:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, sq_ref, sk_ref,
         dq_ref, dq_acc) = refs
        seg_q, seg_k = sq_ref[0], sk_ref[0]
    else:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
         dq_ref, dq_acc) = refs
        seg_q = seg_k = None
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = (j * block_k <= i * block_q + block_q - 1) if causal \
        else (j >= 0)

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0].astype(jnp.float32)                 # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)                 # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        o = o_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]                                 # [bq]
        s = jax.lax.dot_general(q * sm_scale, k,
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        mask = _tile_mask(i, j, block_q, block_k, causal, seg_q, seg_k)
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        # exact per-lane softmax weights from the saved log-sum-exp —
        # no rescaling in the backward, each tile's p is final
        p = jnp.exp(s - lse[:, None])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        p = jnp.where(jnp.isfinite(lse)[:, None], p, 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = jnp.sum(do * o, axis=-1, keepdims=True)     # [bq, 1]
        ds = p * (dp - delta) * sm_scale                    # [bq, bk]
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == k_tiles - 1)
    def _write():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _bw_dkv_kernel(*refs, causal: bool, block_q: int, block_k: int,
                   sm_scale: float, segmented: bool, q_tiles: int):
    if segmented:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, sq_ref, sk_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        seg_q, seg_k = sq_ref[0], sk_ref[0]
    else:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        seg_q = seg_k = None
    j, i = pl.program_id(2), pl.program_id(3)   # key tile outer here

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = (j * block_k <= i * block_q + block_q - 1) if causal \
        else (i >= 0)

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0].astype(jnp.float32)                 # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)                 # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        o = o_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        s = jax.lax.dot_general(q * sm_scale, k,
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        mask = _tile_mask(i, j, block_q, block_k, causal, seg_q, seg_k)
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        p = jnp.where(jnp.isfinite(lse)[:, None], p, 0.0)
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        delta = jnp.sum(do * o, axis=-1, keepdims=True)
        ds = p * (dp - delta) * sm_scale
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == q_tiles - 1)
    def _write():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bw_bwd_call(q, k, v, o, do, lse, segment_ids, causal, sm_scale,
                 block_q, block_k, interpret):
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    q_tiles, k_tiles = s // block_q, s // block_k
    segmented = segment_ids is not None
    q_tile = pl.BlockSpec((1, 1, block_q, d),
                          lambda b_, h_, i, j: (b_, h_, i, 0))
    k_tile = pl.BlockSpec((1, 1, block_k, d),
                          lambda b_, h_, i, j: (b_, h_, j, 0))
    lse_tile = pl.BlockSpec((1, 1, block_q),
                            lambda b_, h_, i, j: (b_, h_, i))
    seg = [] if not segmented else [segment_ids.astype(jnp.int32),
                                    segment_ids.astype(jnp.int32)]

    # pass 1 — dq: query tile outer, key tiles stream innermost
    in_specs = [q_tile, k_tile, k_tile, q_tile, q_tile, lse_tile]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, block_q), lambda b_, h_, i, j: (b_, i)),
            pl.BlockSpec((1, block_k), lambda b_, h_, i, j: (b_, j)),
        ]
    dq = pl.pallas_call(
        functools.partial(_bw_dq_kernel, causal=causal,
                          block_q=block_q, block_k=block_k,
                          sm_scale=sm_scale, segmented=segmented,
                          k_tiles=k_tiles),
        grid=(b, h, q_tiles, k_tiles),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_bw_compiler_params(),
        interpret=interpret,
    )(q, k, v, o, do, lse, *seg)

    # pass 2 — dk/dv: key tile outer, query tiles stream innermost
    # (grid ids arrive as (b, h, j, i) so the index maps swap)
    q_tile2 = pl.BlockSpec((1, 1, block_q, d),
                           lambda b_, h_, j, i: (b_, h_, i, 0))
    k_tile2 = pl.BlockSpec((1, 1, block_k, d),
                           lambda b_, h_, j, i: (b_, h_, j, 0))
    lse_tile2 = pl.BlockSpec((1, 1, block_q),
                             lambda b_, h_, j, i: (b_, h_, i))
    in_specs = [q_tile2, k_tile2, k_tile2, q_tile2, q_tile2, lse_tile2]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, block_q), lambda b_, h_, j, i: (b_, i)),
            pl.BlockSpec((1, block_k), lambda b_, h_, j, i: (b_, j)),
        ]
    dk, dv = pl.pallas_call(
        functools.partial(_bw_dkv_kernel, causal=causal,
                          block_q=block_q, block_k=block_k,
                          sm_scale=sm_scale, segmented=segmented,
                          q_tiles=q_tiles),
        grid=(b, h, k_tiles, q_tiles),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_bw_compiler_params(),
        interpret=interpret,
    )(q, k, v, o, do, lse, *seg)
    return dq, dk, dv


def _bw_compiler_params():
    """Both inner grid axes carry VMEM scratch across iterations, so
    they are "arbitrary" (sequential); batch and heads stay
    parallel."""
    return tpu_compiler_params(
        ("parallel", "parallel", "arbitrary", "arbitrary"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _blockwise(q, k, v, segment_ids, causal, sm_scale, block_q,
               block_k, interpret):
    out, _ = _bw_fwd_call(q, k, v, segment_ids, causal, sm_scale,
                          block_q, block_k, interpret)
    return out


def _blockwise_fwd(q, k, v, segment_ids, causal, sm_scale, block_q,
                   block_k, interpret):
    out, lse = _bw_fwd_call(q, k, v, segment_ids, causal, sm_scale,
                            block_q, block_k, interpret)
    return out, (q, k, v, out, lse, segment_ids)


def _blockwise_bwd(causal, sm_scale, block_q, block_k, interpret, res,
                   g):
    q, k, v, out, lse, segment_ids = res
    dq, dk, dv = _bw_bwd_call(q, k, v, out, g, lse, segment_ids,
                              causal, sm_scale, block_q, block_k,
                              interpret)
    return dq, dk, dv, None


_blockwise.defvjp(_blockwise_fwd, _blockwise_bwd)


def blockwise_flash_attention(q, k, v, segment_ids=None, *,
                              causal: bool = False,
                              sm_scale: float = None,
                              block_q: int = 128, block_k: int = 128,
                              interpret: bool = False):
    """Blockwise (online-softmax) flash attention over ``[B, H, S, D]``
    q/k/v — the long-context form whose VMEM working set is
    independent of S (section comment above has the rescaling
    math and why its contract is tolerance, not bitwise).
    Differentiable via the two-pass tiled backward. Use through
    :func:`bigdl_tpu.kernels.attention`, which owns eligibility, the
    VMEM-budget routing and the jnp fallback."""
    if q.ndim != 4:
        raise ValueError(f"blockwise_flash_attention wants [B,H,S,D], "
                         f"got {q.shape}")
    s, d = q.shape[-2], q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    block_q = fit_block(s, block_q)
    block_k = fit_block(s, block_k)
    return _blockwise(q, k, v, segment_ids, bool(causal),
                      float(sm_scale), int(block_q), int(block_k),
                      bool(interpret))


def flash_attention(q, k, v, segment_ids=None, *, causal: bool = False,
                    sm_scale: float = None, block_q: int = 128,
                    interpret: bool = False):
    """Flash attention over ``[B, H, S, D]`` q/k/v (module docstring
    has the memory/exactness contract). ``segment_ids`` is the packed
    slab's ``[B, S]`` int32 plane — queries attend same-segment keys
    only, ANDed with ``causal``. Differentiable via the fused backward
    kernel; ``interpret`` runs the pallas interpreter (the CPU tier-1
    path). Use through :func:`bigdl_tpu.kernels.attention`, which
    owns eligibility and the jnp fallback."""
    if q.ndim != 4:
        raise ValueError(f"flash_attention wants [B,H,S,D], got "
                         f"{q.shape}")
    s, d = q.shape[-2], q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    block_q = fit_block(s, block_q)
    return _flash(q, k, v, segment_ids, bool(causal), float(sm_scale),
                  int(block_q), bool(interpret))
