"""Fused flash attention for training — pallas, segment-mask aware.

The [S, S] score matrix never exists in memory: the grid tiles the
query axis and each program holds one ``[block_q, S]`` score strip in
VMEM, computes a numerically-stable softmax over the full key axis and
contracts straight into the ``[block_q, D]`` output — O(S·block_q)
live bytes instead of O(S²) (the memory property that lets S=32K run
where the einsum path dies; nn/attention's ``_FLASH_SCORE_BYTES``
measurement note). The backward recomputes the strip from the saved
log-sum-exp and accumulates dK/dV across query tiles in VMEM scratch —
no residual score matrix either.

**Why full-row reductions instead of blockwise rescaling:** the
classic online-softmax rescales the running accumulator by
``exp(m_old - m_new)`` at every key block, which makes the result
depend on where block boundaries fall. Packed training slabs
(``bigdl_tpu.datapipe.packing``) put documents at arbitrary row
offsets, and the datapipe's contract is that a packed forward is
**bit-exact per token** against each document run alone — a guarantee
blockwise rescaling breaks (the rescale rounds differently per
offset). Reducing each query's full key row at once keeps masked
positions as *exact zeros* in the sum, which commutes with document
offset, so the packed-slab bitwise contract survives the kernel
(tests/test_kernels.py asserts it per token). The decode kernel
(:mod:`bigdl_tpu.kernels.decode_attention`), whose win is *skipping*
tail key blocks, uses the true online rescaling form — its contract is
tolerance, not bitwise.

Masking: ``causal`` and/or ``segment_ids`` (``[B, S]`` int32; queries
attend only same-segment keys — the packed-slab mask). Masked scores
are ``-inf`` so they vanish exactly from max/sum; a fully-masked query
row yields 0 output, not NaN.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bigdl_tpu.kernels.common import fit_block, tpu_compiler_params

__all__ = ["flash_attention", "fit_block"]

_NEG_INF = float("-inf")


def _mask_for(i, block_q, s, causal, seg_q, seg_k):
    """The boolean keep-mask for query tile ``i``: ``[block_q, s]``,
    or None when nothing masks."""
    mask = None
    if causal:
        rows = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, s), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, s), 1)
        mask = cols <= rows
    if seg_q is not None:
        seg = seg_q[:, None] == seg_k[None, :]
        mask = seg if mask is None else mask & seg
    return mask


def _fwd_kernel(*refs, causal: bool, block_q: int, sm_scale: float,
                segmented: bool):
    if segmented:
        q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref, lse_ref = refs
        seg_q, seg_k = sq_ref[0], sk_ref[0]
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        seg_q = seg_k = None
    i = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale          # [bq, D]
    k = k_ref[0, 0]                                         # [S, D]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    mask = _mask_for(i, block_q, s.shape[-1], causal, seg_q, seg_k)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                  # [bq, 1]
    # exp(-inf - -inf) = nan on fully-masked rows; the where() zeroes
    # every masked lane EXACTLY, which is what keeps packed slabs
    # bit-faithful (module docstring)
    p = jnp.exp(s - m)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)                  # [bq, 1]
    acc = jax.lax.dot_general(p, v_ref[0, 0].astype(jnp.float32),
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    o_ref[0, 0] = jnp.where(l > 0, acc / l, 0.0).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.where(l[:, 0] > 0, m[:, 0] + jnp.log(l[:, 0]),
                              _NEG_INF)


def _bwd_kernel(*refs, causal: bool, block_q: int, sm_scale: float,
                segmented: bool, q_tiles: int):
    if segmented:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, sq_ref, sk_ref,
         dq_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
        seg_q, seg_k = sq_ref[0], sk_ref[0]
    else:
        (q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
         dq_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
        seg_q = seg_k = None
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q = q_ref[0, 0].astype(jnp.float32)                     # [bq, D]
    k = k_ref[0, 0].astype(jnp.float32)                     # [S, D]
    v = v_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)                   # [bq, D]
    o = o_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]                                     # [bq]
    s = jax.lax.dot_general(q * sm_scale, k,
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    mask = _mask_for(i, block_q, s.shape[-1], causal, seg_q, seg_k)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    # softmax weights straight from the saved log-sum-exp; masked (and
    # fully-masked: -inf - -inf = nan) lanes zeroed exactly
    p = jnp.exp(s - lse[:, None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    p = jnp.where(jnp.isfinite(lse)[:, None], p, 0.0)
    dv_acc[...] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    delta = jnp.sum(do * o, axis=-1, keepdims=True)         # [bq, 1]
    ds = p * (dp - delta) * sm_scale                        # [bq, S]
    dq_ref[0, 0] = jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_acc[...] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)

    @pl.when(i == q_tiles - 1)
    def _write():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _fwd_call(q, k, v, segment_ids, causal, sm_scale, block_q,
              interpret):
    b, h, s, d = q.shape
    grid = (b, h, s // block_q)
    segmented = segment_ids is not None
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i: (b_, h_, i, 0)),
        pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
        pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
    ]
    args = [q, k, v]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, block_q), lambda b_, h_, i: (b_, i)),
            pl.BlockSpec((1, s), lambda b_, h_, i: (b_, 0)),
        ]
        args += [segment_ids.astype(jnp.int32),
                 segment_ids.astype(jnp.int32)]
    kernel = functools.partial(_fwd_kernel, causal=causal,
                               block_q=block_q, sm_scale=sm_scale,
                               segmented=segmented)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b_, h_, i: (b_, h_, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(*args)


def _bwd_call(q, k, v, o, do, lse, segment_ids, causal, sm_scale,
              block_q, interpret):
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    q_tiles = s // block_q
    grid = (b, h, q_tiles)
    segmented = segment_ids is not None
    tile = pl.BlockSpec((1, 1, block_q, d),
                        lambda b_, h_, i: (b_, h_, i, 0))
    full = pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0))
    in_specs = [tile, full, full, tile, tile,
                pl.BlockSpec((1, 1, block_q),
                             lambda b_, h_, i: (b_, h_, i))]
    args = [q, k, v, o, do, lse]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, block_q), lambda b_, h_, i: (b_, i)),
            pl.BlockSpec((1, s), lambda b_, h_, i: (b_, 0)),
        ]
        args += [segment_ids.astype(jnp.int32),
                 segment_ids.astype(jnp.int32)]
    kernel = functools.partial(_bwd_kernel, causal=causal,
                               block_q=block_q, sm_scale=sm_scale,
                               segmented=segmented, q_tiles=q_tiles)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[tile, full, full],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((s, d), jnp.float32),
                        pltpu.VMEM((s, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(*args)


def _compiler_params():
    """q tiles iterate innermost and carry the backward's dK/dV
    scratch, so that axis is "arbitrary" (sequential); batch and heads
    are parallel."""
    return tpu_compiler_params(("parallel", "parallel", "arbitrary"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, segment_ids, causal, sm_scale, block_q, interpret):
    out, _ = _fwd_call(q, k, v, segment_ids, causal, sm_scale, block_q,
                       interpret)
    return out


def _flash_fwd(q, k, v, segment_ids, causal, sm_scale, block_q,
               interpret):
    out, lse = _fwd_call(q, k, v, segment_ids, causal, sm_scale,
                         block_q, interpret)
    return out, (q, k, v, out, lse, segment_ids)


def _flash_bwd(causal, sm_scale, block_q, interpret, res, g):
    q, k, v, out, lse, segment_ids = res
    dq, dk, dv = _bwd_call(q, k, v, out, g, lse, segment_ids, causal,
                           sm_scale, block_q, interpret)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, segment_ids=None, *, causal: bool = False,
                    sm_scale: float = None, block_q: int = 128,
                    interpret: bool = False):
    """Flash attention over ``[B, H, S, D]`` q/k/v (module docstring
    has the memory/exactness contract). ``segment_ids`` is the packed
    slab's ``[B, S]`` int32 plane — queries attend same-segment keys
    only, ANDed with ``causal``. Differentiable via the fused backward
    kernel; ``interpret`` runs the pallas interpreter (the CPU tier-1
    path). Use through :func:`bigdl_tpu.kernels.attention`, which
    owns eligibility and the jnp fallback."""
    if q.ndim != 4:
        raise ValueError(f"flash_attention wants [B,H,S,D], got "
                         f"{q.shape}")
    s, d = q.shape[-2], q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    block_q = fit_block(s, block_q)
    return _flash(q, k, v, segment_ids, bool(causal), float(sm_scale),
                  int(block_q), bool(interpret))
