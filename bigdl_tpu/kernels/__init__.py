"""Pallas kernel layer — the hand-tuned L0 the reference built in C++.

The source system's bottom layer was native kernels behind JNI (Intel
MKL + the BigQuant int8 GEMM, PAPER.md L0); the TPU-native analogue is
``jax.experimental.pallas``. This package holds the kernels and the
ONE gate in front of them:

- :mod:`~bigdl_tpu.kernels.flash_attention` — fused flash attention
  for training: q-tiled, segment-mask aware (packed datapipe slabs run
  bit-faithfully), custom-VJP backward, no materialized [S, S];
- :mod:`~bigdl_tpu.kernels.ragged_decode` — ragged decode
  attention for the generation engine: reads only ``lengths[i]`` valid
  KV per slot instead of the bucket max;
- :mod:`~bigdl_tpu.kernels.int8_gemm` — fused dequant-int8-GEMM
  completing the BigQuant serving story over the calibrated scales;
- :mod:`~bigdl_tpu.kernels.dispatch` — :func:`attention` /
  :func:`decode_attention` / :func:`int8_matmul`: config + shape
  eligibility in, kernel result or None (= run your jnp path) out;
- :mod:`~bigdl_tpu.kernels.config` — :class:`KernelConfig` and the
  ``BIGDL_KERNELS`` env toggle; decode + int8 default ON on real TPU
  (flash stays opt-in until the bench KERNELS trajectory justifies
  it), everything OFF on CPU, and kernels run under the pallas
  *interpreter* everywhere but real TPU so tier-1 on CPU executes the
  real kernel bodies.

Every kernel ships with an interpret-mode equivalence test against the
pure-jnp fallback (tests/test_kernels.py; bitwise for the int8 core
and the greedy decode token stream, tolerance-bounded for softmax
reductions) and registers its programs with a ``kernel=pallas|
reference`` label in :mod:`bigdl_tpu.telemetry.programs` so MFU/HBM
gauges compare the two paths side by side. See docs/kernels.md.
"""
from bigdl_tpu.kernels.config import (KernelConfig, active_label,
                                      configure, enabled, get_config,
                                      interpret_mode, use)
from bigdl_tpu.kernels.dispatch import (attention, decode_attention,
                                        int8_matmul)

__all__ = ["KernelConfig", "configure", "get_config", "use", "enabled",
           "interpret_mode", "active_label", "attention",
           "decode_attention", "int8_matmul"]
