"""Helpers shared by the kernel implementations (tiling + compiler
params) — one home so a jax rename or a tiling policy change is fixed
in exactly one place."""
from __future__ import annotations

__all__ = ["fit_block", "tpu_compiler_params"]


def fit_block(dim: int, preferred: int) -> int:
    """The largest block size <= ``preferred`` that divides ``dim``
    (pallas grids need exact tiling; ragged test shapes shrink the
    tile instead of falling off the kernel path)."""
    b = min(int(preferred), int(dim))
    while dim % b:
        b -= 1
    return b


def tpu_compiler_params(dimension_semantics):
    """TPU compiler params for a kernel grid: the accumulator-carrying
    axis is "arbitrary" (sequential), everything else parallel. (jax
    renamed CompilerParams across versions — resolve whichever this
    one ships.)"""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return cls(dimension_semantics=tuple(dimension_semantics))
