"""Paged ragged decode — per-slot KV read through a page table.

:mod:`bigdl_tpu.kernels.ragged_decode` already bounds decode reads by
``lengths[i]``, but it still assumes each slot's KV rows live in ONE
contiguous ``[T, D]`` stripe of the preallocated cache. At long
context that contiguity is the allocator's enemy: a 128K ``max_len``
cache must reserve the full stripe per slot up front, so slot count —
the continuous-batching width — is priced at the worst case even when
most requests are short. The paged form breaks the stripe into fixed
``page_size`` **blocks** owned by a shared pool:

- ``k_pages``/``v_pages`` are ``[num_pages, H, page_size, D]`` pools;
- ``page_table [slots, pages_per_slot]`` holds each slot's physical
  page ids, in sequence order;
- ``lengths [slots]`` is the same host ragged bound the contiguous
  kernel reads.

The kernel walks grid ``(slots, heads, pages_per_slot)`` with the page
table **scalar-prefetched** (``PrefetchScalarGridSpec``): page ``j`` of
slot ``s`` is fetched by BlockSpec index map ``table[s, j]`` — the
indirection costs an SMEM read at grid-index time, not a gather — and
folded into the online-softmax carry exactly like one ``block_k`` tile
of the contiguous kernel. Pages past ``cdiv(lengths[s], page_size)``
are skipped (``pl.when``), so the per-step read volume stays
``O(lengths[s])`` regardless of how long the pool is.

Token identity: for any page table, the kernel computes the same
online-softmax reduction as the contiguous kernel over the rows the
table names, so decoding through a paged view of a contiguous cache is
**token-identical** to contiguous decode (asserted in
tests/test_longctx.py, shuffled tables included — bitwise vs the
ragged kernel when ``page == block_k``).

:func:`paged_view` builds the ``(pool, table)`` pair from a contiguous
``[slots, H, T, D]`` cache slice — the bridge the tests and the
dispatch escape hatch use; a production long-context allocator owns
its pool directly and hands the table over.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bigdl_tpu.kernels.common import tpu_compiler_params

__all__ = ["paged_decode_attention", "paged_view"]

_NEG_INF = float("-inf")


def paged_view(k, v, page_size: int):
    """Reshape one contiguous ``[slots, H, T, D]`` cache slice into a
    ``(k_pages, v_pages, page_table)`` paged triple: page ``j`` of
    slot ``s`` is rows ``[j*page_size, (j+1)*page_size)`` and the
    identity table maps it to pool id ``s * (T // page_size) + j``.
    ``page_size`` must divide ``T``. (Test/bridge utility — a real
    paged allocator owns the pool; the kernel only sees the table.)"""
    slots, h, t, d = k.shape
    if t % page_size:
        raise ValueError(f"page_size={page_size} must divide the "
                         f"cache time axis T={t}")
    pages_per_slot = t // page_size

    def pool(x):
        x = x.reshape(slots, h, pages_per_slot, page_size, d)
        return x.transpose(0, 2, 1, 3, 4).reshape(
            slots * pages_per_slot, h, page_size, d)

    table = jnp.arange(slots * pages_per_slot, dtype=jnp.int32).reshape(
        slots, pages_per_slot)
    return pool(k), pool(v), table


def _paged_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, page_size: int,
                  pages_per_slot: int, sm_scale: float):
    slot, j = pl.program_id(0), pl.program_id(2)
    n = len_ref[slot]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # pages wholly past the slot's valid rows contribute nothing —
    # skip the flops AND the rescale (the carry is already exact)
    @pl.when(j * page_size < n)
    def _tile():
        q = q_ref[0, 0].reshape(1, -1).astype(jnp.float32) * sm_scale
        kb = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        col = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        s = jnp.where(col < n, s, _NEG_INF)
        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # page 0 column 0 is always valid (lengths clamped >= 1), so
        # m_new is finite from the first live page on and alpha's
        # exp(-inf - finite) underflows to an exact 0 for the
        # zero-initialized carry — same first-tile story as the
        # contiguous kernel's fori_loop
        alpha = jnp.exp(m - m_new)
        p = jnp.where(col < n, jnp.exp(s - m_new), 0.0)
        vb = v_ref[0, 0].astype(jnp.float32)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1,
                                                  keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == pages_per_slot - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[...] / l_ref[...])[0].astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths, *,
                           sm_scale: float = None,
                           interpret: bool = False):
    """One decode step of attention over PAGED KV: ``q`` is
    ``[slots, H, D]`` (one token per slot), ``k_pages``/``v_pages``
    ``[num_pages, H, page_size, D]`` pools, ``page_table`` the int32
    ``[slots, pages_per_slot]`` physical page ids in sequence order,
    ``lengths`` the host int32 ``[slots]`` ragged bound (clamped into
    ``[1, pages_per_slot * page_size]`` — a free slot reads one
    garbage page whose output is never consumed). Returns
    ``[slots, H, D]``. Table entries past a slot's valid pages are
    never fetched beyond block-index resolution — keep them in
    ``[0, num_pages)`` (the identity view does)."""
    from jax.experimental.pallas import tpu as pltpu

    slots, h, d = q.shape
    num_pages, hk, page_size, dk = k_pages.shape
    if (hk, dk) != (h, d) or v_pages.shape != k_pages.shape:
        raise ValueError(f"page pools {k_pages.shape}/{v_pages.shape} "
                         f"do not match q [{slots},{h},{d}]")
    pages_per_slot = int(page_table.shape[1])
    if page_table.shape[0] != slots:
        raise ValueError(f"page_table {page_table.shape} does not "
                         f"match {slots} slots")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    lengths = jnp.clip(lengths.astype(jnp.int32), 1,
                       pages_per_slot * page_size)
    kernel = functools.partial(
        _paged_kernel, page_size=page_size,
        pages_per_slot=pages_per_slot, sm_scale=float(sm_scale))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(slots, h, pages_per_slot),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda s, h_, j, tbl, ln: (s, h_, 0)),
            # the paged read: page j of slot s lives at pool id
            # table[s, j] — the indirection IS the index map
            pl.BlockSpec((1, 1, page_size, d),
                         lambda s, h_, j, tbl, ln: (tbl[s, j], h_, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda s, h_, j, tbl, ln: (tbl[s, j], h_, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d),
                               lambda s, h_, j, tbl, ln: (s, h_, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),   # running max
            pltpu.VMEM((1, 1), jnp.float32),   # running denominator
            pltpu.VMEM((1, d), jnp.float32),   # output accumulator
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, h, d), q.dtype),
        compiler_params=tpu_compiler_params(
            ("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths, q, k_pages, v_pages)
