"""Kernel selection policy: which pallas kernels run, and how.

The reference's L0 was a build-time choice (MKL JNI vs BigQuant C++,
SURVEY.md §1); ours is a *runtime policy*: a :class:`KernelConfig`
names which pallas kernels the dispatch layer
(:mod:`bigdl_tpu.kernels.dispatch`) may select, everything else runs
the pure-jnp reference path. The default is resolved lazily from the
backend — **decode + int8 on on real TPU** (pure wins over work the
reference cannot skip), **flash opt-in even there** (the measured
einsum numbers in ``nn/attention`` still win at the lengths it can
hold), **everything off on CPU** — and the ``BIGDL_KERNELS`` env var
overrides it without touching code:

- ``BIGDL_KERNELS=1`` / ``on`` / ``all`` — every kernel on;
- ``BIGDL_KERNELS=0`` / ``off`` — every kernel off;
- ``BIGDL_KERNELS=flash,decode`` — a comma subset of
  ``flash`` / ``decode`` / ``int8``.

``interpret`` (``None`` = auto) runs the kernels through the pallas
interpreter instead of Mosaic — auto means *interpret everywhere but
real TPU*, which is how tier-1 on CPU executes the real kernel bodies
(docs/kernels.md "Interpret-mode testing").

The active config is read at TRACE time: a compiled program bakes in
the kernel choice that was active when it was built (the serving
``CompileCache`` keys programs per servable, so a toggle never mutates
an already-compiled program — build a fresh engine/service to switch).
"""
from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass
from typing import Iterator, Optional

__all__ = ["KernelConfig", "configure", "get_config", "use", "enabled",
           "interpret_mode", "active_label"]

#: the ops a config can enable, in the order the env parser accepts
_OPS = ("flash", "decode", "int8")


@dataclass(frozen=True)
class KernelConfig:
    """Which pallas kernels the dispatch layer may select.

    ``flash_attention`` — the tiled flash-attention training kernel;
    ``decode_attention`` — the ragged decode kernel (reads only
    ``lengths[i]`` valid KV per slot); ``int8_matmul`` — the fused
    dequant-int8-GEMM serving kernel. ``interpret=None`` auto-selects
    the pallas interpreter off-TPU; ``block_q``/``block_k`` are
    preferred tile sizes (shrunk to the largest divisor of the actual
    dimension, so ragged test shapes stay eligible)."""

    flash_attention: bool = False
    decode_attention: bool = False
    int8_matmul: bool = False
    interpret: Optional[bool] = None
    block_q: int = 128
    block_k: int = 128
    #: compiled-mode VMEM working-set budget (MiB) for one flash
    #: program; ``None`` reads ``BIGDL_VMEM_BUDGET_MB`` and falls back
    #: to the measured 12 MiB default (dispatch module docstring has
    #: the budget math)
    vmem_budget_mb: Optional[int] = None
    #: whether shapes past the VMEM budget route to the blockwise
    #: long-context flash kernel (key dimension tiled through VMEM)
    #: instead of declining to the einsum reference
    long_context: bool = True

    @classmethod
    def all_on(cls, **kw) -> "KernelConfig":
        """Every kernel enabled — ``BIGDL_KERNELS=1`` and the test/
        bench on-legs. (The real-TPU *default* is decode + int8 only;
        flash stays opt-in there until the bench KERNELS trajectory
        justifies the flip — see the module docstring.)"""
        return cls(flash_attention=True, decode_attention=True,
                   int8_matmul=True, **kw)

    @classmethod
    def off(cls) -> "KernelConfig":
        """Every kernel disabled — the pure-jnp reference everywhere
        (the CPU default)."""
        return cls()

    @classmethod
    def from_env(cls, value: str) -> "KernelConfig":
        """Parse a ``BIGDL_KERNELS`` value (module docstring has the
        grammar); unknown op names raise so a typo cannot silently run
        the slow path."""
        v = value.strip().lower()
        if v in ("1", "on", "all", "true"):
            return cls.all_on()
        if v in ("0", "off", "false", "none", ""):
            return cls.off()
        ops = {p.strip() for p in v.split(",") if p.strip()}
        unknown = ops - set(_OPS)
        if unknown:
            raise ValueError(
                f"BIGDL_KERNELS={value!r}: unknown kernel(s) "
                f"{sorted(unknown)} (choose from {list(_OPS)}, "
                "or 1/on/all, 0/off)")
        return cls(flash_attention="flash" in ops,
                   decode_attention="decode" in ops,
                   int8_matmul="int8" in ops)

    @property
    def any_enabled(self) -> bool:
        """Whether any kernel is selected at all."""
        return (self.flash_attention or self.decode_attention
                or self.int8_matmul)

    def resolve_interpret(self) -> bool:
        """The effective interpret flag: auto (``None``) means
        interpret everywhere but real TPU."""
        if self.interpret is not None:
            return bool(self.interpret)
        import jax
        return jax.default_backend() != "tpu"

    def resolve_vmem_budget(self) -> int:
        """The effective flash VMEM budget in BYTES: an explicit
        ``vmem_budget_mb`` wins, else ``BIGDL_VMEM_BUDGET_MB``, else
        the 12 MiB default the PR 11 kernel shipped with."""
        mb = self.vmem_budget_mb
        if mb is None:
            env = os.environ.get("BIGDL_VMEM_BUDGET_MB")
            if env is not None:
                try:
                    mb = int(env)
                except ValueError:
                    raise ValueError(
                        f"BIGDL_VMEM_BUDGET_MB={env!r} is not an "
                        f"integer MiB count") from None
        if mb is None:
            mb = 12
        if mb <= 0:
            raise ValueError(
                f"flash VMEM budget must be positive, got {mb} MiB")
        return mb * 1024 * 1024


_LOCK = threading.Lock()
_CONFIG: Optional[KernelConfig] = None  # None = resolve default lazily


def _default() -> KernelConfig:
    env = os.environ.get("BIGDL_KERNELS")
    if env is not None:
        return KernelConfig.from_env(env)
    import jax
    if jax.default_backend() == "tpu":
        # decode + int8 are pure wins (they replace work the einsum
        # path cannot skip); flash stays OPT-IN on TPU because the
        # measured numbers in nn/attention (_FLASH_SCORE_BYTES note)
        # show XLA's fused einsum winning wall-clock at every length
        # it can hold — promote it via BIGDL_KERNELS=1/flash once the
        # bench KERNELS trajectory on real TPU justifies the flip
        return KernelConfig(decode_attention=True, int8_matmul=True)
    return KernelConfig.off()


def get_config() -> KernelConfig:
    """The active :class:`KernelConfig` (resolving the backend/env
    default on first use)."""
    global _CONFIG
    with _LOCK:
        if _CONFIG is None:
            _CONFIG = _default()
        return _CONFIG


def configure(config: Optional[KernelConfig]) -> None:
    """Install ``config`` as the active kernel policy; ``None``
    restores the backend/env default (re-resolved lazily)."""
    global _CONFIG
    with _LOCK:
        _CONFIG = config


@contextlib.contextmanager
def use(config: KernelConfig) -> Iterator[KernelConfig]:
    """Scoped :func:`configure`: the previous policy is restored on
    exit — the tests' and bench legs' on/off toggle."""
    global _CONFIG
    with _LOCK:
        prev = _CONFIG
        _CONFIG = config
    try:
        yield config
    finally:
        with _LOCK:
            _CONFIG = prev


def enabled(op: str) -> bool:
    """Whether kernel ``op`` (``flash`` | ``decode`` | ``int8``) is
    enabled under the active config."""
    cfg = get_config()
    try:
        return {"flash": cfg.flash_attention,
                "decode": cfg.decode_attention,
                "int8": cfg.int8_matmul}[op]
    except KeyError:
        raise ValueError(f"unknown kernel op {op!r} "
                         f"(choose from {list(_OPS)})") from None


def interpret_mode() -> bool:
    """The active config's effective interpret flag."""
    return get_config().resolve_interpret()


def active_label() -> str:
    """``"pallas"`` when any kernel is enabled, else ``"reference"`` —
    the ``kernel=`` label value program profiles carry so MFU/HBM
    gauges compare the two paths side by side
    (:mod:`bigdl_tpu.telemetry.programs`)."""
    return "pallas" if get_config().any_enabled else "reference"


