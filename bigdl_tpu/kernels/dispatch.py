"""The kernel dispatch layer — every pallas kernel enters here.

Call sites (``nn.attention``, the generation decode path,
``nn.quantized``) never invoke ``pl.pallas_call`` directly — the
``raw-pallas-call`` lint rule enforces it — they ask this layer, which
checks the active :class:`~bigdl_tpu.kernels.config.KernelConfig` and
shape eligibility and returns either the kernel result or **None**,
meaning "run your existing pure-jnp path". Returning None (rather than
owning a second copy of the reference math) keeps exactly ONE
reference implementation per op — the einsum/`ops.quant` code the
equivalence tests compare against — and guarantees the kernels-off
configuration is byte-identical to the pre-kernel tree.

Dispatch decisions happen at TRACE time (config and shapes are
static), so the per-trace counters below count compiled-program
routing, not per-step calls: ``kernels/dispatch/pallas`` (label
``op=flash|decode|int8``) vs ``kernels/dispatch/reference`` (labels
``op=...`` plus ``reason=config|shape|vmem`` so a `diagnose` dump
attributes every decline).
"""
from __future__ import annotations

import threading
from typing import Optional

import jax.numpy as jnp

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.kernels import config as _config
from bigdl_tpu.kernels.common import fit_block

__all__ = ["attention", "decode_attention", "paged_decode_attention",
           "int8_matmul", "taken_in_thread"]

# module-level registration so `tools.check --telemetry-audit` sees the
# REAL instruments on import, not a hand-maintained name list
_C_PALLAS = telemetry.counter(
    "kernels/dispatch/pallas",
    "traces routed to a pallas kernel (label op=flash|decode|int8)")
_C_REFERENCE = telemetry.counter(
    "kernels/dispatch/reference",
    "traces declined by the dispatch layer to the pure-jnp reference "
    "(labels op=flash|decode|int8, reason=config|shape|vmem)")


# trace-scoped routing evidence: tracing happens on the caller's
# thread, so a thread-local tick lets a compile site ask "did THIS
# trace route through a pallas kernel" — which is how program profiles
# earn their kernel=pallas label (telemetry.programs), instead of
# guessing from the global config
_TRACE = threading.local()


def taken_in_thread() -> int:
    """Monotonic count of pallas dispatches taken on this thread —
    snapshot before and after a ``lower()``/trace to learn whether the
    traced program actually contains a kernel."""
    return getattr(_TRACE, "taken", 0)


def _declined(op: str, reason: str) -> None:
    # reason= makes declines attributable in `diagnose`: "config" (the
    # active KernelConfig disabled the op), "shape" (ineligible dtype/
    # rank/alignment), "vmem" (over the flash working-set budget with
    # the blockwise long-context path switched off)
    _C_REFERENCE.inc(op=op, reason=reason)


def _taken(op: str) -> None:
    _C_PALLAS.inc(op=op)
    _TRACE.taken = getattr(_TRACE, "taken", 0) + 1


def _floating(*arrays) -> bool:
    return all(jnp.issubdtype(a.dtype, jnp.floating) for a in arrays)


def _flash_vmem_bytes(q, block_q: int) -> int:
    """Upper-bound VMEM working set of ONE flash grid program — the
    BACKWARD kernel's, which dominates: f32 casts of the full K and V
    blocks, the two [S, D] f32 dK/dV scratch accumulators, and four
    f32 [block_q, S] strips (scores, p, dp, ds). The forward (K+V at
    input dtype + three strips) is strictly smaller, so budgeting on
    the backward keeps jax.grad from OOMing at shapes the forward
    alone would have accepted."""
    s, d = q.shape[-2], q.shape[-1]
    bq = fit_block(s, block_q)
    kv_inputs = 2 * s * d * q.dtype.itemsize
    kv_f32 = 2 * s * d * 4        # in-kernel f32 casts of K and V
    scratch = 2 * s * d * 4       # dK/dV accumulators
    strips = 4 * bq * s * 4       # scores / p / dp / ds
    tiles = 4 * bq * d * 4        # q, o, do, dq tiles
    return kv_inputs + kv_f32 + scratch + strips + tiles


def attention(q, k, v, *, causal: bool = False, segment_ids=None,
              sm_scale: Optional[float] = None):
    """Flash-attention dispatch for ``[B, H, S, D]`` q/k/v: the tiled
    pallas kernel (:mod:`bigdl_tpu.kernels.flash_attention`, segment-
    mask aware, differentiable) when the active config enables
    ``flash`` and the shapes qualify — else **None**, telling the
    caller to run its jnp path (``nn.attention.dot_product_attention``
    falls through to the einsum form, which itself still routes
    HBM-busting lengths to jax's bundled flash kernel)."""
    if not _config.enabled("flash"):
        _declined("flash", "config")
        return None
    if (q.ndim != 4 or k.shape != q.shape or v.shape != q.shape
            or not _floating(q, k, v)):
        _declined("flash", "shape")
        return None
    cfg = _config.get_config()
    interpret = cfg.resolve_interpret()
    if _flash_vmem_bytes(q, cfg.block_q) > cfg.resolve_vmem_budget():
        # past the working-set budget the full-K-row kernel would OOM
        # Mosaic (an error, not a fallback): route to the blockwise
        # long-context kernel — key axis tiled through VMEM with
        # online-softmax rescaling — unless it is switched off, in
        # which case decline so nn.attention's einsum/bundled-flash
        # routes keep the escape hatch. The budget gate applies in
        # interpret mode too, so CPU tier-1 exercises the same routing
        # a TPU would take (shrink vmem_budget_mb to steer small test
        # shapes down the blockwise path).
        if not cfg.long_context:
            _declined("flash", "vmem")
            return None
        from bigdl_tpu.kernels.flash_attention import (
            blockwise_flash_attention)

        _taken("flash")
        return blockwise_flash_attention(
            q, k, v, segment_ids, causal=causal, sm_scale=sm_scale,
            block_q=cfg.block_q, block_k=cfg.block_k,
            interpret=interpret)
    from bigdl_tpu.kernels.flash_attention import flash_attention

    _taken("flash")
    return flash_attention(q, k, v, segment_ids, causal=causal,
                           sm_scale=sm_scale, block_q=cfg.block_q,
                           interpret=interpret)


def decode_attention(q, k, v, lengths, *,
                     sm_scale: Optional[float] = None):
    """Ragged-decode dispatch: ``q [slots, H, D]`` (one token per
    slot), ``k``/``v`` ``[slots, H, T, D]`` cache slices, ``lengths``
    the host per-slot valid-KV vector. Returns the kernel result
    (:mod:`bigdl_tpu.kernels.ragged_decode` — reads only
    ``lengths[i]`` rows per slot) when ``decode`` is enabled and the
    shapes qualify, else **None** (the caller's length-masked einsum
    path runs)."""
    if not _config.enabled("decode"):
        _declined("decode", "config")
        return None
    if (k.ndim != 4 or q.shape != k.shape[:2] + k.shape[3:]
            or not _floating(q, k, v)):
        _declined("decode", "shape")
        return None
    from bigdl_tpu.kernels.ragged_decode import ragged_decode_attention

    cfg = _config.get_config()
    _taken("decode")
    return ragged_decode_attention(q, k, v, lengths, sm_scale=sm_scale,
                                   block_k=cfg.block_k,
                                   interpret=cfg.resolve_interpret())


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths, *,
                           sm_scale: Optional[float] = None):
    """Paged ragged-decode dispatch: ``q [slots, H, D]`` one token per
    slot, ``k_pages``/``v_pages`` ``[num_pages, H, page_size, D]``
    pools, ``page_table [slots, pages_per_slot]`` physical page ids,
    ``lengths`` the host ragged bound. Returns the kernel result
    (:mod:`bigdl_tpu.kernels.paged_decode` — table-indirect page reads,
    token-identical to contiguous decode) when ``decode`` is enabled
    and the shapes qualify, else **None** (the caller gathers its
    contiguous view and runs the reference path)."""
    if not _config.enabled("decode"):
        _declined("decode", "config")
        return None
    if (k_pages.ndim != 4 or v_pages.shape != k_pages.shape
            or q.ndim != 3
            or q.shape[1:] != (k_pages.shape[1], k_pages.shape[3])
            or page_table.ndim != 2
            or page_table.shape[0] != q.shape[0]
            or not _floating(q, k_pages, v_pages)):
        _declined("decode", "shape")
        return None
    from bigdl_tpu.kernels.paged_decode import (
        paged_decode_attention as _paged)

    cfg = _config.get_config()
    _taken("decode")
    return _paged(q, k_pages, v_pages, page_table, lengths,
                  sm_scale=sm_scale, interpret=cfg.resolve_interpret())


#: compiled (non-interpret) int8 tiles must fill the MXU: the same
#: alignment gate nn.quantized always applied before taking the kernel
_INT8_ALIGN = (256, 256, 512)


def int8_matmul(x_q, w_q, x_scale, w_scale, bias=None):
    """Fused dequant-int8-GEMM dispatch: ``x_q [M, K] i8 @ w_q [N, K]
    i8^T`` rescaled by ``x_scale`` (per row or scalar — the calibrated
    serving path) and per-channel ``w_scale``. Returns the pallas
    kernel result (bias added OUTSIDE the kernel so the path stays
    bit-identical to dequantize-then-matmul — see
    :mod:`bigdl_tpu.kernels.int8_gemm`) when ``int8`` is enabled and
    the shapes qualify, else **None** (the caller runs
    ``ops.quant.quantized_linear``)."""
    if not _config.enabled("int8"):
        _declined("int8", "config")
        return None
    m, k = x_q.shape
    n = w_q.shape[0]
    cfg = _config.get_config()
    interpret = cfg.resolve_interpret()
    if not interpret and not (m % _INT8_ALIGN[0] == 0
                              and n % _INT8_ALIGN[1] == 0
                              and k % _INT8_ALIGN[2] == 0):
        _declined("int8", "shape")
        return None
    from bigdl_tpu.kernels.int8_gemm import pallas_quantized_matmul

    _taken("int8")
    xs = jnp.broadcast_to(
        jnp.asarray(x_scale, jnp.float32).reshape(-1, 1), (m, 1))
    out = pallas_quantized_matmul(x_q, w_q, xs, w_scale,
                                  interpret=interpret)
    if bias is not None:
        # the ONE bias add both paths share (fusing it into the kernel
        # costs a one-ulp FMA drift vs the reference; int8_gemm.py)
        out = out + bias.reshape(1, -1).astype(jnp.float32)
    return out
