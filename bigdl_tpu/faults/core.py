"""Deterministic fault injection: named faultpoints + scripted schedules.

Code under test declares **faultpoints** — named host-side call sites
(``faults.point("ckpt/write_manifest", neval=4)``) at the exact spots
where real systems die: mid-checkpoint-write, inside a download attempt,
on the serving dispatch thread. A test (or the chaos CLI) **arms** a
:class:`FaultSchedule` scripting what each point does: fail on the Nth
matching call, fail with a seeded probability, inject latency, raise a
chosen exception type, or SIGKILL the process — the same scripted-death
technique the reference used for its fault-tolerance suite
(ExceptionTest / TestUtils.scala:103-131), made a reusable subsystem.

Disarmed is the default and costs one module-flag check per call (the
``telemetry.span`` discipline — safe to leave in production hot loops;
a micro-benchmark test asserts the bound). Armed, every fired fault
lands in the ``faults/point/injected`` telemetry counter (labelled
``point=<name>``), so recovery becomes a *reconcilable* invariant: the
chaos CLI asserts injected faults == observed recoveries, counter for
counter.

Schedules are deterministic by construction: per-rule call counters and
per-rule seeded RNGs — the same schedule against the same workload
injects the same faults, which is what lets the chaos soak demand
bit-identical final params.

String syntax (``parse_schedule``)::

    point=opt,opt,...;point=opt,...

    train/step=nth:3,raise:RuntimeError        # 3rd call raises
    fetch/download=nth:1-2,raise:OSError       # calls 1 and 2 raise
    serving/dispatch=prob:0.5,seed:7,times:2   # seeded coin, max twice
    prefetch/stage=delay:20                    # inject 20ms latency
    ckpt/write_manifest=match:neval=4,sigkill  # SIGKILL at neval 4
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import bigdl_tpu.telemetry as telemetry

_INJECTED = telemetry.counter(
    "faults/point/injected",
    "faults fired by the armed schedule (labelled point=<name>)")


class InjectedFault(RuntimeError):
    """The default exception an armed faultpoint raises (classified
    transient by :func:`bigdl_tpu.faults.retry.classify`, so recovery
    paths exercise their real retry logic)."""


#: exception types a schedule string may name (``raise:OSError``);
#: programmatic rules accept any exception class directly
NAMED_EXCEPTIONS: Dict[str, type] = {
    "InjectedFault": InjectedFault,
    "RuntimeError": RuntimeError,
    "OSError": OSError,
    "IOError": OSError,
    "ConnectionError": ConnectionError,
    "TimeoutError": TimeoutError,
    "ValueError": ValueError,
    "TypeError": TypeError,
}

_ACTIONS = ("raise", "sigkill", "delay")

#: every faultpoint name compiled into the library, with the failure
#: it models — the named-point table. A schedule may only script
#: points listed here (typo'd schedules silently never fire, which is
#: the opposite of deterministic chaos), tests assert membership when
#: adding a point, and docs/robustness.md mirrors this table.
KNOWN_POINTS: Dict[str, str] = {
    "ckpt/write_manifest": "between checkpoint payload and manifest "
                           "commit (torn-write window)",
    "train/step": "inside one optimizer step (mid-training death)",
    "fetch/download": "inside one dataset download attempt",
    "prefetch/stage": "inside one prefetch staging copy",
    "datapipe/read": "inside one datapipe shard read",
    "serving/dispatch": "on the serving dispatch thread",
    "serving/take_batch": "taking a batch off the admission queue",
    "serving/swap": "inside a model-version hot-swap",
    "serving/decode": "inside one continuous-batching decode step",
    "file_io/remote_write": "inside one remote (non-local) write",
    "fleet/route": "at the router's placement edge",
    "fleet/replica": "at a replica's submit path (injection here IS "
                     "that replica's death)",
    "fleet/verify": "inside speculative-decode verification",
    "fleet/spawn": "at the autoscaler's spawn actuation, before the "
                   "replica is built (aborted scale-up)",
    "fleet/drain": "at the autoscaler's drain actuation, before the "
                   "drain starts (aborted scale-down)",
    "fleet/deploy": "at every deploy state-machine transition, "
                    "before it commits",
    "fleet/canary_swap": "at each incumbent's hot-swap during a "
                         "fleet-wide deploy (aborted swap reverts "
                         "the already-swapped)",
}


class FaultRule:
    """One scripted behavior for one faultpoint.

    ``when`` is the conjunction of every given matcher: call number
    (``nth`` — a (first, last) inclusive range over MATCHING calls),
    seeded probability (``prob``/``seed``), and context equality
    (``match`` — compared against the kwargs the call site passes).
    ``times`` bounds total fires. ``action`` is ``"raise"`` (with
    ``exc``), ``"sigkill"``, or ``"delay"``; ``delay_ms`` latency is
    injected before any action (so a rule can be pure latency)."""

    def __init__(self, point: str, *, action: str = "raise",
                 exc: type = InjectedFault, nth=None,
                 prob: Optional[float] = None, seed: int = 0,
                 times: Optional[int] = None,
                 match: Optional[Dict[str, Any]] = None,
                 predicate: Optional[Callable[[Dict[str, Any]], bool]]
                 = None,
                 delay_ms: float = 0.0):
        if action not in _ACTIONS:
            raise ValueError(
                f"action must be one of {_ACTIONS}, got {action!r}")
        if isinstance(nth, int):
            nth = (nth, nth)
        self.point = point
        self.action = action
        self.exc = exc
        self.nth = nth
        self.prob = prob
        self.times = times
        self.match = dict(match) if match else None
        self.predicate = predicate
        self.delay_ms = float(delay_ms)
        self._rng = random.Random(seed)
        self.calls = 0   # matching-context calls seen
        self.fired = 0   # faults actually injected

    def consider(self, ctx: Dict[str, Any]) -> bool:
        """Whether this rule would fire for one call. Advances the
        rule's deterministic state (matching-call counter, seeded RNG)
        but not ``fired`` — every rule for a point observes every call,
        so ``nth`` counting never depends on sibling-rule order; the
        caller records the one winning fire. Caller holds the schedule
        lock."""
        if self.match is not None and any(
                ctx.get(k) != v for k, v in self.match.items()):
            return False
        if self.predicate is not None and not self.predicate(ctx):
            return False
        self.calls += 1
        ok = True
        if self.times is not None and self.fired >= self.times:
            ok = False
        if self.nth is not None and not (
                self.nth[0] <= self.calls <= self.nth[1]):
            ok = False
        if self.prob is not None and self._rng.random() >= self.prob:
            ok = False
        return ok

    def __repr__(self) -> str:
        return (f"FaultRule({self.point!r}, action={self.action!r}, "
                f"nth={self.nth}, prob={self.prob}, times={self.times}, "
                f"match={self.match}, fired={self.fired})")


class FaultSchedule:
    """An ordered set of :class:`FaultRule`; the first rule that fires
    for a call wins. ``fired()`` reports per-point injection counts —
    the numbers the chaos CLI reconciles against recovery counters."""

    def __init__(self, rules: Optional[List[FaultRule]] = None):
        self.rules: List[FaultRule] = list(rules or [])

    def add(self, rule: FaultRule) -> "FaultSchedule":
        """Append one rule; returns self for chaining."""
        self.rules.append(rule)
        return self

    def fired(self) -> Dict[str, int]:
        """Per-point counts of faults this schedule injected."""
        out: Dict[str, int] = {}
        for r in self.rules:
            out[r.point] = out.get(r.point, 0) + r.fired
        return out

    def total_fired(self) -> int:
        """Total faults injected across every rule."""
        return sum(r.fired for r in self.rules)


def _parse_rule(spec: str) -> FaultRule:
    point, _, opts = spec.partition("=")
    point = point.strip()
    if not point or not opts:
        raise ValueError(
            f"bad fault spec {spec!r}: want point=opt,opt,...")
    kw: Dict[str, Any] = {}
    for opt in opts.split(","):
        opt = opt.strip()
        key, _, val = opt.partition(":")
        if key == "raise":
            kw["action"] = "raise"
            if val:
                if val not in NAMED_EXCEPTIONS:
                    raise ValueError(
                        f"unknown exception {val!r} (one of "
                        f"{sorted(NAMED_EXCEPTIONS)})")
                kw["exc"] = NAMED_EXCEPTIONS[val]
        elif key == "sigkill":
            kw["action"] = "sigkill"
        elif key == "delay":
            kw.setdefault("action", "delay")
            kw["delay_ms"] = float(val)
        elif key == "nth":
            lo, _, hi = val.partition("-")
            kw["nth"] = (int(lo), int(hi) if hi else int(lo))
        elif key == "prob":
            kw["prob"] = float(val)
        elif key == "seed":
            kw["seed"] = int(val)
        elif key == "times":
            kw["times"] = int(val)
        elif key == "match":
            mk, _, mv = val.partition("=")
            m = kw.setdefault("match", {})
            try:
                m[mk] = int(mv)
            except ValueError:
                m[mk] = mv
        else:
            raise ValueError(f"unknown fault option {opt!r} in {spec!r}")
    return FaultRule(point, **kw)


def parse_schedule(text: str) -> FaultSchedule:
    """Parse the compact ``point=opt,...;point=opt,...`` schedule string
    (module docstring has the grammar) into a :class:`FaultSchedule`."""
    rules = [_parse_rule(s) for s in text.split(";") if s.strip()]
    if not rules:
        raise ValueError(f"empty fault schedule {text!r}")
    return FaultSchedule(rules)


# -- the armed-schedule singleton ----------------------------------------
# _ARMED is the ONE flag the disarmed point() fast path reads (same
# discipline as telemetry._ENABLED); everything else sits behind it.
_ARMED = False
_SCHEDULE: Optional[FaultSchedule] = None
_LOCK = threading.Lock()


def is_armed() -> bool:
    """Whether a fault schedule is currently armed."""
    return _ARMED


def active_schedule() -> Optional[FaultSchedule]:
    """The armed schedule (None when disarmed) — read its ``fired()``
    to reconcile injections against recovery counters."""
    return _SCHEDULE


def arm(schedule) -> FaultSchedule:
    """Arm a :class:`FaultSchedule` (or a schedule string, parsed via
    :func:`parse_schedule`). Replaces any armed schedule; returns it.
    Arming is always an explicit call — there is no env-var-only path,
    so a stray variable inherited from a test environment can never
    fault a real run (the ``arm_scripted_crash`` double-opt-in)."""
    global _ARMED, _SCHEDULE
    if isinstance(schedule, str):
        schedule = parse_schedule(schedule)
    with _LOCK:
        _SCHEDULE = schedule
        _ARMED = True
    return schedule


def disarm() -> None:
    """Disarm fault injection; the schedule stays readable via
    :func:`active_schedule` for post-run reconciliation."""
    global _ARMED
    with _LOCK:
        _ARMED = False


class _Armed:
    """Context manager form of arm()/disarm() for tests."""

    def __init__(self, schedule):
        self.schedule = arm(schedule)

    def __enter__(self) -> FaultSchedule:
        return self.schedule

    def __exit__(self, *exc) -> None:
        disarm()


def armed(schedule) -> _Armed:
    """``with faults.armed("train/step=nth:2,raise"):`` — arm for the
    block, disarm on exit; yields the parsed :class:`FaultSchedule`."""
    return _Armed(schedule)


def injected_total() -> int:
    """Total faults the armed (or last-armed) schedule injected."""
    s = _SCHEDULE
    return s.total_fired() if s is not None else 0


def point(name: str, /, **ctx) -> None:
    """Declare a faultpoint: no-op unless a schedule is armed AND has a
    rule for ``name`` whose matchers accept this call. The disarmed
    path is one module-flag check — hot-loop safe.

    Armed behavior per the winning rule: optional injected latency
    (``delay_ms``), then ``raise`` its exception, ``sigkill`` this
    process, or return (pure-latency rules). Every fired fault counts
    into ``faults/point/injected`` (label ``point=<name>``) *before*
    acting, so even a SIGKILL is visible to the registry snapshot a
    surviving exporter holds."""
    if not _ARMED:
        return
    with _LOCK:
        sched = _SCHEDULE
        if sched is None:
            return
        hit = None
        for r in sched.rules:
            # every rule for the point observes the call (counters and
            # seeded RNGs advance deterministically); the FIRST rule
            # that fires wins and records it
            if r.point == name and r.consider(ctx) and hit is None:
                hit = r
        if hit is not None:
            hit.fired += 1
    if hit is None:
        return
    _INJECTED.inc(point=name)
    from bigdl_tpu.telemetry import flight
    flight.note("fault", point=name, action=hit.action)
    if hit.delay_ms:
        time.sleep(hit.delay_ms / 1000.0)
    if hit.action == "sigkill":
        # the sigkill-adjacent flight dump: the bundle on disk is the
        # only thing that survives the next line
        flight.on_fatal(f"faults/{name}")
        import signal
        os.kill(os.getpid(), signal.SIGKILL)
    if hit.action == "raise":
        raise hit.exc(
            f"injected fault at {name!r} (call {hit.calls}, ctx {ctx})")
