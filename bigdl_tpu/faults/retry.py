"""Classified retry with exponential backoff + jitter.

The reference's driver retried EVERY failure on a fixed interval
(DistriOptimizer.scala:789-855); a structurally broken model fails
identically on attempt 5 as on attempt 1, and a fleet of workers
retrying on the same fixed clock stampedes whatever just recovered.
This module is the shared policy both the optimizer's
retry-from-checkpoint loop and the IO paths (dataset download, remote
writes) apply instead:

- :func:`classify` splits exceptions into **fatal** (structural /
  compile-shaped: wrong types, missing attributes, shape mismatches —
  retrying cannot fix them, fail fast with the original diagnostic)
  and **transient** (IO, runtime, injected faults — retry);
- :func:`backoff_delay` doubles a base interval per attempt up to a
  cap, with equal-jitter randomization so synchronized retriers spread
  out;
- :func:`retry_call` wraps one callable with both, counting every
  retried attempt into the ``io/retry/retries`` telemetry counter (the
  number the chaos CLI reconciles against injected IO faults).
"""
from __future__ import annotations

import logging
import random
import time
from typing import Callable, Optional, Tuple

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.faults.core import InjectedFault

logger = logging.getLogger("bigdl_tpu")

_RETRIES = telemetry.counter(
    "io/retry/retries",
    "transient-failure retries performed by retry_call")

# jitter source when the caller passes no seeded rng: a private
# instance (never the global stdlib stream — callers wanting
# reproducible schedules pass their own random.Random(seed))
_JITTER_RNG = random.Random()

#: structural / compile-shaped errors: retrying replays the identical
#: failure, so fail fast with the first (clearest) diagnostic. Checked
#: BEFORE the transient set — NotImplementedError subclasses
#: RuntimeError, and jax's concretization/type errors subclass
#: TypeError/ValueError, so order is what keeps them fatal.
FATAL_TYPES: Tuple[type, ...] = (
    TypeError, ValueError, KeyError, IndexError, AttributeError,
    NotImplementedError, ImportError, SyntaxError, MemoryError,
)

#: plausibly-environmental errors worth retrying: IO and connectivity,
#: generic runtime failures (XlaRuntimeError subclasses RuntimeError),
#: and injected faults (so recovery paths exercise their real logic).
TRANSIENT_TYPES: Tuple[type, ...] = (
    OSError, ConnectionError, TimeoutError, RuntimeError, InjectedFault,
)


def classify(exc: BaseException) -> str:
    """``"fatal"`` or ``"transient"`` for one exception.

    Fatal types win over transient ones (a ``NotImplementedError`` IS
    a ``RuntimeError``); an exception carrying ``bigdl_fatal = True``
    (e.g. ``CheckpointCorrupt`` escaping a quarantine-impossible
    resume) is fatal regardless of its base class; unknown exception
    types default to transient — the reference retried everything, and
    a retry that re-raises is strictly more informative than a
    fast-fail on a recoverable blip."""
    if getattr(exc, "bigdl_fatal", False):
        return "fatal"
    if isinstance(exc, FATAL_TYPES):
        return "fatal"
    if isinstance(exc, TRANSIENT_TYPES):
        return "transient"
    return "transient"


def is_transient(exc: BaseException) -> bool:
    """True when :func:`classify` says the exception is retryable."""
    return classify(exc) == "transient"


def backoff_delay(attempt: int, base_s: float, max_s: float = 30.0,
                  rng: Optional[random.Random] = None) -> float:
    """Seconds to sleep before retry number ``attempt`` (0-based):
    ``base * 2**attempt`` capped at ``max_s``, equal-jittered into
    ``[d/2, d)`` so synchronized retriers don't stampede. Pass a seeded
    ``rng`` for reproducible schedules."""
    d = min(float(base_s) * (2.0 ** attempt), float(max_s))
    r = (rng if rng is not None else _JITTER_RNG).random()
    return d / 2.0 + d / 2.0 * r


def retry_call(fn: Callable, *args, attempts: int = 3,
               base_delay_s: float = 0.5, max_delay_s: float = 30.0,
               rng: Optional[random.Random] = None,
               describe: str = "", sleep: Callable[[float], None]
               = time.sleep, **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying classified-transient
    failures up to ``attempts`` total tries with
    :func:`backoff_delay` sleeps between them. Fatal errors and the
    final transient failure re-raise unchanged. Each performed retry
    increments ``io/retry/retries`` and logs a warning naming
    ``describe`` (defaults to the callable's name)."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    what = describe or getattr(fn, "__name__", "call")
    for attempt in range(attempts):
        try:
            return fn(*args, **kwargs)
        except Exception as e:
            if classify(e) == "fatal" or attempt == attempts - 1:
                raise
            delay = backoff_delay(attempt, base_delay_s, max_delay_s,
                                  rng)
            _RETRIES.inc()
            logger.warning(
                "%s failed (%s: %s); retry %d/%d in %.2fs", what,
                type(e).__name__, e, attempt + 1, attempts - 1, delay)
            sleep(delay)
