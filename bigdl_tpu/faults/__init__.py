"""bigdl_tpu.faults — deterministic fault injection + hardened recovery.

Failure is an *input* here, not an accident: named faultpoints sit at
the exact sites where real systems die (mid-checkpoint-write, inside a
download attempt, on the serving dispatch thread), and a seeded
:class:`FaultSchedule` scripts what each one does — fail on the Nth
call, fail with a seeded probability, inject latency, raise a chosen
exception, or SIGKILL the process. Disarmed (the default) every
faultpoint is one module-flag check; armed, every fired fault lands in
the ``faults/point/injected`` telemetry counter so the chaos CLI
(``python -m bigdl_tpu.tools.chaos``) can assert injections reconcile
exactly against recovery counters. See docs/robustness.md.

Usage::

    from bigdl_tpu import faults

    # in library code, at the site where a real system would die:
    faults.point("fetch/download", url=url)

    # in a test / the chaos CLI:
    with faults.armed("fetch/download=nth:1-2,raise:OSError"):
        mnist_read_data_sets(tmpdir)          # retries, then succeeds

The sibling :mod:`bigdl_tpu.faults.retry` module is the recovery half:
exception classification (fatal-fast vs transient-retry) and
exponential backoff + jitter, shared by the optimizer's
retry-from-checkpoint loop and the IO paths.
"""
from bigdl_tpu.faults.core import (KNOWN_POINTS, NAMED_EXCEPTIONS,
                                   FaultRule, FaultSchedule,
                                   InjectedFault, active_schedule, arm,
                                   armed, disarm, injected_total,
                                   is_armed, parse_schedule, point)
from bigdl_tpu.faults.retry import (FATAL_TYPES, TRANSIENT_TYPES,
                                    backoff_delay, classify, is_transient,
                                    retry_call)

__all__ = [
    "FaultRule", "FaultSchedule", "InjectedFault", "KNOWN_POINTS",
    "NAMED_EXCEPTIONS",
    "active_schedule", "arm", "armed", "disarm", "injected_total",
    "is_armed", "parse_schedule", "point",
    "FATAL_TYPES", "TRANSIENT_TYPES", "backoff_delay", "classify",
    "is_transient", "retry_call",
]
