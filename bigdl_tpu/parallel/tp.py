"""Tensor/expert-parallel sharding via GSPMD rules.

The reference's only parallelism is data-parallel allreduce
(AllReduceParameter, SURVEY.md §2.3); TP/EP here is additive TPU-first
scope. Mechanism: param-path regex → PartitionSpec rules; ``shard_params``
lays the pytree out over the mesh and XLA's SPMD partitioner inserts the
collectives (all-gather/reduce-scatter over ICI) at compile time — no
hand-written comms.
"""
from __future__ import annotations

import re
import time
from typing import List, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import bigdl_tpu.telemetry as telemetry

_SHARD_PARAMS_S = telemetry.histogram(
    "parallel/tp/shard_params_s",
    "seconds laying a param tree out over the mesh")
_SHARD_OPT_S = telemetry.histogram(
    "parallel/tp/shard_opt_state_s",
    "seconds laying ZeRO-1 optimizer state out over the mesh")

Rules = Sequence[Tuple[str, P]]


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def spec_for(path: str, ndim: int, rules: Rules) -> P:
    """First rule whose regex matches AND whose spec rank fits the leaf.

    P() (replicated) matches any rank; otherwise the spec must have
    exactly ``ndim`` entries.
    """
    for pattern, spec in rules:
        if re.search(pattern, path):
            if len(spec) == 0 or len(spec) == ndim:
                return spec
    return P()


def tree_shardings(tree, mesh: Mesh, rules: Rules):
    """Pytree of NamedShardings matching ``tree``'s structure."""
    def leaf_sharding(path, leaf):
        spec = spec_for(_path_str(path), np.ndim(leaf), rules)
        return NamedSharding(mesh, spec)
    return jax.tree_util.tree_map_with_path(leaf_sharding, tree)


def put_global(leaf, sharding):
    """device_put that also works on meshes spanning other processes
    (multi-host: device_put cannot target non-addressable devices, so
    each process materializes its shards via callback from the full
    host value it holds)."""
    if jax.process_count() > 1:
        a = np.asarray(leaf)
        return jax.make_array_from_callback(a.shape, sharding,
                                            lambda idx: a[idx])
    return jax.device_put(leaf, sharding)


def shard_params(params, mesh: Mesh, rules: Rules):
    """Place the param pytree according to the rules (multi-host-safe).

    The host→mesh placement cost (the boundary where AllReduceParameter
    paid its BlockManager shuffle) is recorded as a
    ``parallel/shard_params`` span and the
    ``parallel/tp/shard_params_s`` telemetry histogram."""
    t0 = time.perf_counter()
    with telemetry.span("parallel/shard_params"):
        out = jax.tree.map(put_global, params,
                           tree_shardings(params, mesh, rules))
    _SHARD_PARAMS_S.observe(time.perf_counter() - t0)
    return out


def validate_rules(params, mesh: Mesh, rules: Rules) -> List[str]:
    """Sanity-check: every sharded dim must divide evenly. Returns a list
    of problem descriptions (empty = all good)."""
    problems = []
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in flat:
        p = _path_str(path)
        spec = spec_for(p, np.ndim(leaf), rules)
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            axes = (axis,) if isinstance(axis, str) else tuple(axis)
            size = int(np.prod([mesh.shape[a] for a in axes]))
            if np.shape(leaf)[dim] % size != 0:
                problems.append(
                    f"{p}: dim {dim} ({np.shape(leaf)[dim]}) not divisible "
                    f"by mesh axes {axes} (size {size})")
    return problems


def shard_opt_state_zero1(tree, mesh: Mesh, data_axis: str = "data"):
    """ZeRO-1 optimizer-state layout: each moment buffer's first
    divisibly-sized dim sharded over the data axis, else replicated —
    the analogue of the reference's per-node owned weight shard running
    the OptimMethod (AllReduceParameter.scala:214-303). EVERY leaf —
    including non-float step counters — gets an explicit NamedSharding,
    so a donated ``jax.jit`` update's inferred out-shardings can never
    silently re-replicate a shard after the first step (the full
    stage-1/2/3 policy lives in ``parallel/zero.py``; this keeps the
    original one-call helper)."""
    from bigdl_tpu.parallel.zero import ZeroConfig, shard_zero_tree

    t0 = time.perf_counter()
    with telemetry.span("parallel/shard_opt_state_zero1"):
        out = shard_zero_tree(tree, mesh,
                              ZeroConfig(stage=1, data_axis=data_axis))
    _SHARD_OPT_S.observe(time.perf_counter() - t0)
    return out
