from bigdl_tpu.parallel.mesh import (
    make_mesh, data_parallel_mesh, replicated, batch_sharded)
