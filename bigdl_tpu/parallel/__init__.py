"""Parallelism: mesh/sharding helpers, ring attention (SP/CP), tensor/
expert-parallel rules (TP/EP). The reference's only axis is DP
(AllReduceParameter); everything else is additive TPU-first scope."""
from bigdl_tpu.parallel.mesh import (
    make_mesh, data_parallel_mesh, replicated, batch_sharded)
from bigdl_tpu.parallel.ring_attention import (
    ring_attention, ring_attention_sharded)
from bigdl_tpu.parallel.ulysses import (
    ulysses_attention, ulysses_attention_sharded)
from bigdl_tpu.parallel.sequence import (
    SeqParallelConfig, active_sequence_parallel,
    sequence_parallel_available, use_sequence_parallel)
from bigdl_tpu.parallel.tp import (
    shard_params, shard_opt_state_zero1, spec_for, tree_shardings,
    validate_rules)
from bigdl_tpu.parallel.pipeline import pipeline_forward, spmd_pipeline
from bigdl_tpu.parallel.zero import (
    ZeroConfig, collective_counts, constrain_base, constrain_zero,
    place_zero_opt_state, place_zero_params, place_zero_state,
    record_memory_gauges, reduce_scatter_evidence, shard_zero_tree,
    tree_bytes_per_chip, tree_zero_specs, window_collectives)
