"""Mesh & sharding helpers (replaces BigDL's AllReduceParameter topology).

The reference's communication pattern (AllReduceParameter.scala:214-303) is
reduce-scatter -> per-shard optimizer -> all-gather over Spark BlockManager.
On TPU the same semantics are a single ``psum`` (or
``psum_scatter``/``all_gather`` pair for ZeRO-1) over the ICI mesh; XLA
inserts and schedules the collectives from sharding annotations.
"""
from __future__ import annotations

import functools

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    """Build a mesh from named axis sizes; devices default to all."""
    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(axis_sizes))
    if n != len(devices):
        raise ValueError(f"mesh wants {n} devices, have {len(devices)}")
    return Mesh(np.array(devices).reshape(tuple(axis_sizes)),
                tuple(axis_names))


def data_parallel_mesh(devices=None) -> Mesh:
    """1-D `data` mesh over all devices — the AllReduceParameter analogue."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), ("data",))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated NamedSharding on ``mesh``."""
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Batch-dim-sharded NamedSharding over ``axis``."""
    return NamedSharding(mesh, P(axis))


def resolve_axis_mesh(mesh: Optional[Mesh], axis: str) -> Optional[Mesh]:
    """The mesh a parallelism axis actually lives on: the configured
    mesh, else the Engine's — and only when the axis is present with >1
    devices. None means "run the local/dense path"."""
    if mesh is None:
        from bigdl_tpu.utils.engine import Engine
        if Engine.is_initialized():
            mesh = Engine.mesh()
    if mesh is not None and axis in mesh.shape and mesh.shape[axis] > 1:
        return mesh
    return None


@functools.lru_cache(maxsize=32)
def seq_sharded_attention(kern, mesh: Mesh, seq_axis: str, causal: bool,
                          with_segments: bool = False):
    """Jitted partial-manual shard_map wrapper for a sequence-parallel
    attention kernel (``ring_attention`` / ``ulysses_attention``):
    [B,H,S,D] with S manual over ``seq_axis``, every other mesh axis
    left auto so batch/model dims compose with DP/TP under GSPMD. With
    ``with_segments`` the wrapper takes a fourth [B, S] packed-segment
    argument, sharded over the same axis.

    Cached per (kernel, mesh, axis, causal, segments): callers may
    invoke it every forward without rebuilding or retracing. jit is
    load-bearing — partial-manual shard_map cannot run eagerly; under
    an outer jit it inlines.
    """
    spec = P(None, None, seq_axis, None)
    fn = functools.partial(kern, axis_name=seq_axis, causal=causal)
    if with_segments:
        def with_seg(q, k, v, seg):
            return fn(q, k, v, segments=seg)
        return jax.jit(jax.shard_map(
            with_seg, mesh=mesh,
            in_specs=(spec, spec, spec, P(None, seq_axis)),
            out_specs=spec, axis_names=frozenset({seq_axis}),
            check_vma=False))
    return jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({seq_axis}), check_vma=False))
