"""Mesh & sharding helpers (replaces BigDL's AllReduceParameter topology).

The reference's communication pattern (AllReduceParameter.scala:214-303) is
reduce-scatter -> per-shard optimizer -> all-gather over Spark BlockManager.
On TPU the same semantics are a single ``psum`` (or
``psum_scatter``/``all_gather`` pair for ZeRO-1) over the ICI mesh; XLA
inserts and schedules the collectives from sharding annotations.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    """Build a mesh from named axis sizes; devices default to all."""
    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(axis_sizes))
    if n != len(devices):
        raise ValueError(f"mesh wants {n} devices, have {len(devices)}")
    return Mesh(np.array(devices).reshape(tuple(axis_sizes)),
                tuple(axis_names))


def data_parallel_mesh(devices=None) -> Mesh:
    """1-D `data` mesh over all devices — the AllReduceParameter analogue."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), ("data",))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = "data") -> NamedSharding:
    return NamedSharding(mesh, P(axis))
