"""Sequence-parallel training policy — SP as a train-step concern.

The module-level knob (``MultiHeadAttention(ring_axis=...)``) bakes
sequence parallelism into the MODEL; that is the right shape for a
hand-built network but the wrong one for the Optimizer product path,
where the same model object should train dense on one chip and
sequence-sharded on a mesh without being rebuilt. This module makes SP
a *policy* the train step installs:

- :class:`SeqParallelConfig` names the mesh axis the sequence dim
  shards over and which exact kernel runs it — ``ring``
  (:mod:`bigdl_tpu.parallel.ring_attention`: K/V blocks rotate via
  ``ppermute``, memory linear in the LOCAL length) or ``ulysses``
  (:mod:`bigdl_tpu.parallel.ulysses`: all-to-all head re-sharding,
  full-sequence attention per head group);
- ``build_train_step(seq_parallel=...)`` installs the config for the
  duration of the step TRACE (:func:`use_sequence_parallel` — trace-
  scoped exactly like the kernel dispatch config), and every
  ``MultiHeadAttention`` without an explicit ``ring_axis`` adopts it;
- like ``ZeroConfig``, the policy is a NO-OP when it cannot apply
  (:meth:`SeqParallelConfig.active_on`): no mesh, axis missing or size
  1, or a jax build without ``jax.shard_map`` — the dense path runs
  and the exported ``train/seq_parallel/degree`` gauge says 1.

Composition story (docs/performance.md "Long context"): the SP
collectives live INSIDE the traced step, so under
``set_steps_per_sync(K)`` they land inside the scan body — the
windowed dispatch boundary stays collective-free (the ``[hlo]``
``entry-collective`` check covers ``collective-permute`` and
``all-to-all``) — and ZeRO's gradient reduce-scatter / params gather
compose orthogonally: ZeRO shards the *weight update* over the data
axis, SP shards *attention activations* over the sequence axis.
"""
from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass
from typing import Iterator, Optional

import bigdl_tpu.telemetry as telemetry

__all__ = ["SeqParallelConfig", "use_sequence_parallel",
           "active_sequence_parallel", "sequence_parallel_available"]

#: the axis sizes the active policy actually achieved — 1 means SP is
#: off or could not apply (no mesh / missing axis / no shard_map), so
#: a dashboard reads the degree it is paying for, not the one asked for
_G_DEGREE = telemetry.gauge(
    "train/seq_parallel/degree",
    "active sequence-parallel mesh degree (1 = dense attention)")


def sequence_parallel_available() -> bool:
    """Whether this jax build can run the SP kernels at all
    (``jax.shard_map`` — probed by ``bigdl_tpu.elastic.capability``,
    the same gate tier-1 skips ring/Ulysses tests on)."""
    from bigdl_tpu.elastic.capability import shard_map_available
    return shard_map_available()


@dataclass(frozen=True)
class SeqParallelConfig:
    """Which sequence-parallel kernel runs attention, over which axis.

    ``impl`` — ``"ring"`` or ``"ulysses"`` (module docstring has the
    trade); ``axis`` the mesh axis carrying the sequence dim; ``mesh``
    the mesh it lives on (None resolves the Engine's, matching
    ``MultiHeadAttention``'s own resolution)."""

    axis: str = "seq"
    impl: str = "ring"
    mesh: Optional[object] = None

    def __post_init__(self):
        if self.impl not in ("ring", "ulysses"):
            raise ValueError(
                f"seq-parallel impl must be 'ring' or 'ulysses', got "
                f"{self.impl!r}")

    def resolve_mesh(self):
        """The mesh the policy would actually run on (None = cannot
        apply here)."""
        from bigdl_tpu.parallel.mesh import resolve_axis_mesh
        return resolve_axis_mesh(self.mesh, self.axis)

    def degree(self) -> int:
        """The sequence-shard count the policy achieves on the
        resolved mesh (1 = it will not apply)."""
        mesh = self.resolve_mesh() if sequence_parallel_available() \
            else None
        return int(mesh.shape[self.axis]) if mesh is not None else 1

    def active_on(self, mesh=None) -> bool:
        """Whether the policy applies: shard_map present AND the axis
        splits >1 ways on the resolved mesh. Mirrors
        ``ZeroConfig.active_on`` — an inapplicable policy is a quiet
        no-op, not an error, so one training script serves every
        topology."""
        if not sequence_parallel_available():
            return False
        if mesh is not None and self.mesh is None:
            from bigdl_tpu.parallel.mesh import resolve_axis_mesh
            return resolve_axis_mesh(mesh, self.axis) is not None
        return self.resolve_mesh() is not None

    def kernel(self):
        """The per-shard attention kernel the config names."""
        if self.impl == "ulysses":
            from bigdl_tpu.parallel.ulysses import ulysses_attention
            return ulysses_attention
        from bigdl_tpu.parallel.ring_attention import ring_attention
        return ring_attention


_TLS = threading.local()


def active_sequence_parallel() -> Optional[SeqParallelConfig]:
    """The policy installed on this thread's current trace (None =
    dense). Read by ``MultiHeadAttention.forward_fn`` for modules
    without an explicit ``ring_axis``."""
    return getattr(_TLS, "config", None)


@contextlib.contextmanager
def use_sequence_parallel(
        config: Optional[SeqParallelConfig]
) -> Iterator[Optional[SeqParallelConfig]]:
    """Scoped install of ``config`` as the thread's active policy —
    wrapped around the model apply inside ``build_train_step`` so the
    adoption happens at TRACE time (the compiled program bakes the
    routing in; toggling later never mutates an existing program,
    exactly the kernel-config contract)."""
    prev = getattr(_TLS, "config", None)
    _TLS.config = config
    try:
        yield config
    finally:
        _TLS.config = prev


def record_degree(degree: int) -> None:
    """Export the achieved SP degree (``train/seq_parallel/degree``) —
    called once per ``build_train_step``."""
    _G_DEGREE.set(int(degree))
