"""Pipeline parallelism (GPipe-style) over a ``pipe`` mesh axis — net-new
vs the reference (SURVEY.md §2.3: PP absent). TPU-first design: each
device owns a contiguous stage of stacked homogeneous blocks; microbatches
stream through the ring via ``ppermute`` inside a ``lax.scan`` (the
classic SPMD pipeline pattern), so XLA overlaps the per-stage compute
with the ICI transfer of activations.

Use inside ``shard_map``: params sharded [n_stages, layers/stage, ...]
over ``pipe`` dim 0, inputs microbatched [M, mb, ...] (replicated), output
replicated [M, mb, ...].

Composition: ``pipeline_forward`` maps ONLY the pipe axis (plus any
``extra_axes`` — e.g. a sequence-parallel axis whose ring-attention
collectives must run manually inside the stage) — every other mesh axis
stays auto (GSPMD), so data/tensor/expert parallelism compose freely.
``with_aux=True`` threads a per-block scalar side output (MoE
load-balance loss) through the pipeline: garbage fill/drain steps are
masked out, so the result equals the dense model's
mean-over-microbatches, sum-over-layers aux exactly.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import bigdl_tpu.telemetry as telemetry


def spmd_pipeline(block_fn: Callable, stage_params, x, *,
                  axis_name: str = "pipe", n_stages: int,
                  with_aux: bool = False):
    """Run microbatches through the pipeline. Call under shard_map.

    block_fn(layer_params, x) -> x : one block's forward
        (with_aux: -> (x, aux_scalar)).
    stage_params: pytree with leading dim [layers_per_stage] — THIS
        stage's shard.
    x: [M, mb, ...] microbatched input (replicated across stages).
    Returns [M, mb, ...] outputs (replicated); with_aux additionally a
    scalar: mean over microbatches of the sum of per-layer aux values
    (fill/drain steps that run on garbage buffers are masked out).
    """
    stage = jax.lax.axis_index(axis_name)
    m = x.shape[0]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def apply_stage(xx):
        if with_aux:
            def body(carry, layer_params):
                h, aux = carry
                h, a = block_fn(layer_params, h)
                return (h, aux + a.astype(jnp.float32)), None
            (out, aux), _ = jax.lax.scan(
                body, (xx, jnp.zeros((), jnp.float32)), stage_params)
            return out, aux

        def body(h, layer_params):
            return block_fn(layer_params, h), None
        out, _ = jax.lax.scan(body, xx, stage_params)
        return out, jnp.zeros((), jnp.float32)

    buf0 = jnp.zeros(x.shape[1:], x.dtype)
    out0 = jnp.zeros_like(x)
    if hasattr(jax.lax, "pcast"):
        buf0, out0 = jax.lax.pcast((buf0, out0), (axis_name,),
                                   to="varying")
    elif hasattr(jax.lax, "pvary"):
        buf0, out0 = jax.lax.pvary((buf0, out0), (axis_name,))

    def step(carry, t):
        buf, out, aux = carry
        # stage 0 ingests microbatch t (clamped; tail steps flush)
        inject = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
        buf = jnp.where(stage == 0, inject, buf)
        y, a = apply_stage(buf)
        # stage s processes microbatch (t - s): real only inside the
        # window, fill/drain iterations compute on garbage and must not
        # pollute the aux accumulation
        valid = jnp.logical_and(t >= stage, t - stage < m)
        aux = aux + jnp.where(valid, a, 0.0)
        # last stage writes microbatch (t - (n_stages-1))
        widx = t - (n_stages - 1)
        should = jnp.logical_and(stage == n_stages - 1, widx >= 0)
        upd = jax.lax.dynamic_update_index_in_dim(
            out, y, jnp.clip(widx, 0, m - 1), 0)
        out = jnp.where(should, upd, out)
        # rotate activations one stage down the ring
        y = jax.lax.ppermute(y, axis_name, perm)
        return (y, out, aux), None

    aux0 = jnp.zeros((), jnp.float32)
    if hasattr(jax.lax, "pcast"):
        aux0 = jax.lax.pcast(aux0, (axis_name,), to="varying")
    elif hasattr(jax.lax, "pvary"):
        aux0 = jax.lax.pvary(aux0, (axis_name,))
    (_, out, aux), _ = jax.lax.scan(step, (buf0, out0, aux0),
                                    jnp.arange(m + n_stages - 1))
    # replicate the last stage's outputs to every shard
    out = jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out))
    out = jax.lax.psum(out, axis_name)
    if with_aux:
        # per-stage masked sums -> global sum over (layer, microbatch),
        # then mean over microbatches (matches the dense twin)
        return out, jax.lax.psum(aux, axis_name) / m
    return out


def spmd_pipeline_interleaved(block_fn: Callable, stage_params, x, *,
                              axis_name: str = "pipe", n_stages: int,
                              n_rounds: int, with_aux: bool = False):
    """Interleaved (virtual-stage / Megatron-style) schedule: each stage
    owns ``n_rounds`` NON-contiguous layer chunks and every microbatch
    circles the ring ``n_rounds`` times, so the fill/drain bubble
    shrinks from (S-1)/(M+S-1) to (S-1)/(V·M+S-1) — each fill tick is
    1/V of a GPipe stage's work. Autodiff mirrors the schedule for the
    backward pass. Call under shard_map.

    stage_params: pytree [1, V, layers_per_chunk, ...] — THIS stage's
        shard; chunk v of stage s holds global layer block (v·S + s).
    x: [M, mb, ...] microbatched input (replicated); M must be >= S
        (a round-v activation re-enters stage 0 at tick v·M+m, which
        precedes its arrival when M < S-1+1).
    Returns [M, mb, ...] (+ aux scalar when with_aux), identical math
    to the sequential layer scan.
    """
    stage = jax.lax.axis_index(axis_name)
    m = x.shape[0]
    assert m >= n_stages, (
        f"interleaved schedule needs microbatches >= stages "
        f"({m} < {n_stages})")
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    ticks = n_rounds * m + n_stages - 1

    def apply_chunk(v_idx, xx):
        chunk = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(a[0], v_idx, 0,
                                                   keepdims=False),
            stage_params)
        if with_aux:
            def body(carry, layer_params):
                h, aux = carry
                h, a = block_fn(layer_params, h)
                return (h, aux + a.astype(jnp.float32)), None
            (out, aux), _ = jax.lax.scan(
                body, (xx, jnp.zeros((), jnp.float32)), chunk)
            return out, aux

        def body(h, layer_params):
            return block_fn(layer_params, h), None
        out, _ = jax.lax.scan(body, xx, chunk)
        return out, jnp.zeros((), jnp.float32)

    buf0 = jnp.zeros(x.shape[1:], x.dtype)
    out0 = jnp.zeros_like(x)
    queue0 = jnp.zeros_like(x)  # stage-0 re-entry waiting room
    aux0 = jnp.zeros((), jnp.float32)
    if hasattr(jax.lax, "pcast"):
        buf0, out0, queue0, aux0 = jax.lax.pcast(
            (buf0, out0, queue0, aux0), (axis_name,), to="varying")
    elif hasattr(jax.lax, "pvary"):
        buf0, out0, queue0, aux0 = jax.lax.pvary(
            (buf0, out0, queue0, aux0), (axis_name,))

    def step(carry, t):
        buf, queue, out, aux = carry
        # a round-(v) microbatch m finished stage S-1 at tick v·M+m+S-1
        # and its rotation lands here NOW (tick t = v·M+m+S): park it in
        # slot m until its round-(v+1) start tick (v+1)·M+m
        arr_idx = t - n_stages
        park = jax.lax.dynamic_update_index_in_dim(
            queue, buf, jnp.maximum(arr_idx, 0) % m, 0)
        queue = jnp.where(arr_idx >= 0, park, queue)
        # stage 0 input: round 0 injects externally, later rounds read
        # the waiting room; other stages read the ring buffer
        inject = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
        waiting = jax.lax.dynamic_index_in_dim(
            queue, jnp.clip(t, 0, ticks) % m, axis=0, keepdims=False)
        s0_in = jnp.where(t < m, inject, waiting)
        xx = jnp.where(stage == 0, s0_in, buf)
        # chunk index: stage s at tick t works round v = (t-s)//M
        v_idx = jnp.clip((t - stage) // m, 0, n_rounds - 1)
        y, a = apply_chunk(v_idx, xx)
        valid = jnp.logical_and(t >= stage,
                                t - stage < n_rounds * m)
        aux = aux + jnp.where(valid, a, 0.0)
        # last stage, final round: this microbatch is DONE
        widx = t - stage
        done = jnp.logical_and(stage == n_stages - 1,
                               jnp.logical_and(valid,
                                               widx >= (n_rounds - 1) * m))
        upd = jax.lax.dynamic_update_index_in_dim(
            out, y, jnp.clip(widx - (n_rounds - 1) * m, 0, m - 1), 0)
        out = jnp.where(done, upd, out)
        y = jax.lax.ppermute(y, axis_name, perm)
        return (y, queue, out, aux), None

    (_, _, out, aux), _ = jax.lax.scan(
        step, (buf0, queue0, out0, aux0), jnp.arange(ticks))
    out = jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out))
    out = jax.lax.psum(out, axis_name)
    if with_aux:
        return out, jax.lax.psum(aux, axis_name) / m
    return out


# bounded: entries key on bound methods, pinning the model instance and
# its compiled executable — unbounded growth across repeated model
# construction (tests, sweeps) would leak host memory
@functools.lru_cache(maxsize=32)
def _pipeline_callable(block_fn: Callable, mesh: Mesh, axis_name: str,
                       n_stages: int, x_spec, extra_axes: frozenset,
                       with_aux: bool, schedule: str = "gpipe",
                       n_rounds: int = 1):
    """Cached jitted partial-manual pipeline over ``axis_name`` (+ any
    ``extra_axes`` the stage body runs manual collectives over, e.g. a
    ring-attention seq axis).

    in_specs uses pytree-PREFIX specs, so one cache entry serves any
    stacked-params structure; cache key includes block_fn — pass a
    STABLE callable (a stored bound method, not a fresh lambda) or every
    call recompiles. jit is load-bearing: partial-manual shard_map
    cannot run eagerly; under an outer jit it inlines.
    """
    if schedule == "interleaved":
        fn = functools.partial(spmd_pipeline_interleaved, block_fn,
                               axis_name=axis_name, n_stages=n_stages,
                               n_rounds=n_rounds, with_aux=with_aux)
    else:
        fn = functools.partial(spmd_pipeline, block_fn,
                               axis_name=axis_name, n_stages=n_stages,
                               with_aux=with_aux)
    xs = x_spec if x_spec is not None else P()
    out_specs = (xs, P()) if with_aux else xs
    jitted = jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(axis_name), xs),
        out_specs=out_specs,
        axis_names=frozenset({axis_name}) | extra_axes,
        check_vma=False))
    # program-profile hook (one flag check when profiling is off):
    # eagerly-dispatched pipeline programs register their cost/memory
    # analysis; under an outer jit the wrapper is tracer-transparent
    from bigdl_tpu.telemetry import programs
    return programs.maybe_wrap_jitted(
        f"train/pipeline/{schedule}x{n_stages}", "train", jitted)


def pipeline_forward(block_fn: Callable, stacked_params, x, mesh: Mesh, *,
                     axis_name: str = "pipe", n_microbatches: int,
                     x_spec=None, extra_axes=(), with_aux: bool = False,
                     schedule: str = "gpipe", n_rounds: int = 2):
    """Full-array convenience wrapper — composes with DP/TP/SP/EP.

    stacked_params: pytree with leading dim [n_layers] (n_layers divisible
    by the pipe axis size); x: [batch, ...] (batch divisible by
    n_microbatches). Returns [batch, ...] (with_aux: plus a scalar).

    Only ``axis_name`` (and ``extra_axes``) are mapped manually; every
    OTHER mesh axis stays an auto (GSPMD) axis, so a
    (data × pipe × model) mesh runs the microbatch dim data-parallel and
    the within-block matmuls tensor-parallel with XLA-inserted
    collectives, while activations ride the pipe ring via ppermute —
    DP×TP×PP in one jitted step. A sequence-parallel axis goes in
    ``extra_axes`` with ``x_spec`` sharding the microbatched activations'
    sequence dim (e.g. ``P(None, None, 'seq', None)`` for [M, mb, S, E])
    so the stage body's ring attention runs its own collectives.

    ``schedule="interleaved"`` (with ``n_rounds`` virtual chunks per
    stage) trades the GPipe bubble (stages−1)/(M+stages−1) for
    (stages−1)/(n_rounds·M+stages−1); the stacked params are re-laid
    out [S, V, layers/(S·V), ...] inside the jitted step, so with
    pipe-sharded rules GSPMD inserts one layer-permutation collective
    per step — measure before choosing it for small models.
    """
    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    assert b % n_microbatches == 0, (b, n_microbatches)
    # telemetry marks the host-side entry into the pipeline collective
    # (eager calls only: under an enclosing jit the python here runs
    # once at trace time, where a span would record a lie)
    pspan = telemetry.NOOP_SPAN if isinstance(x, jax.core.Tracer) \
        else telemetry.span("parallel/pipeline_forward",
                            schedule=schedule, stages=n_stages,
                            microbatches=n_microbatches)
    with pspan:
        return _pipeline_forward_impl(block_fn, stacked_params, x, mesh,
                                      axis_name, n_microbatches, x_spec,
                                      extra_axes, with_aux, schedule,
                                      n_rounds, n_stages)


def _pipeline_forward_impl(block_fn, stacked_params, x, mesh, axis_name,
                           n_microbatches, x_spec, extra_axes, with_aux,
                           schedule, n_rounds, n_stages):
    b = x.shape[0]
    mb = b // n_microbatches
    xm = x.reshape((n_microbatches, mb) + x.shape[1:])
    if schedule == "interleaved":
        leading = jax.tree.leaves(stacked_params)[0].shape[0]
        chunk = n_stages * n_rounds
        assert leading % chunk == 0, (leading, n_stages, n_rounds)
        lps = leading // chunk

        def relayout(a):
            a = a.reshape((n_rounds, n_stages, lps) + a.shape[1:])
            return jnp.moveaxis(a, 1, 0)  # [S, V, lps, ...]
        stacked_params = jax.tree.map(relayout, stacked_params)
    else:
        n_rounds = 1
    res = _pipeline_callable(block_fn, mesh, axis_name, n_stages,
                             x_spec, frozenset(extra_axes),
                             with_aux, schedule,
                             n_rounds)(stacked_params, xm)
    if with_aux:
        out, aux = res
        return out.reshape((b,) + out.shape[2:]), aux
    return res.reshape((b,) + res.shape[2:])
