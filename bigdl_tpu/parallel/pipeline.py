"""Pipeline parallelism (GPipe-style) over a ``pipe`` mesh axis — net-new
vs the reference (SURVEY.md §2.3: PP absent). TPU-first design: each
device owns a contiguous stage of stacked homogeneous blocks; microbatches
stream through the ring via ``ppermute`` inside a ``lax.scan`` (the
classic SPMD pipeline pattern), so XLA overlaps the per-stage compute
with the ICI transfer of activations.

Use inside ``shard_map``: params sharded [n_stages, layers/stage, ...]
over ``pipe`` dim 0, inputs microbatched [M, mb, ...] (replicated), output
replicated [M, mb, ...].
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def spmd_pipeline(block_fn: Callable, stage_params, x, *,
                  axis_name: str = "pipe", n_stages: int):
    """Run microbatches through the pipeline. Call under shard_map.

    block_fn(layer_params, x) -> x : one block's forward.
    stage_params: pytree with leading dim [layers_per_stage] — THIS
        stage's shard.
    x: [M, mb, ...] microbatched input (replicated across stages).
    Returns [M, mb, ...] outputs (replicated).
    """
    stage = jax.lax.axis_index(axis_name)
    m = x.shape[0]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def apply_stage(xx):
        def body(h, layer_params):
            return block_fn(layer_params, h), None
        out, _ = jax.lax.scan(body, xx, stage_params)
        return out

    buf0 = jnp.zeros(x.shape[1:], x.dtype)
    out0 = jnp.zeros_like(x)
    if hasattr(jax.lax, "pcast"):
        buf0, out0 = jax.lax.pcast((buf0, out0), (axis_name,),
                                   to="varying")
    elif hasattr(jax.lax, "pvary"):
        buf0, out0 = jax.lax.pvary((buf0, out0), (axis_name,))

    def step(carry, t):
        buf, out = carry
        # stage 0 ingests microbatch t (clamped; tail steps flush)
        inject = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
        buf = jnp.where(stage == 0, inject, buf)
        y = apply_stage(buf)
        # last stage writes microbatch (t - (n_stages-1))
        widx = t - (n_stages - 1)
        should = jnp.logical_and(stage == n_stages - 1, widx >= 0)
        upd = jax.lax.dynamic_update_index_in_dim(
            out, y, jnp.clip(widx, 0, m - 1), 0)
        out = jnp.where(should, upd, out)
        # rotate activations one stage down the ring
        y = jax.lax.ppermute(y, axis_name, perm)
        return (y, out), None

    (_, out), _ = jax.lax.scan(step, (buf0, out0),
                               jnp.arange(m + n_stages - 1))
    # replicate the last stage's outputs to every shard
    out = jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out))
    return jax.lax.psum(out, axis_name)


# bounded: entries key on bound methods, pinning the model instance and
# its compiled executable — unbounded growth across repeated model
# construction (tests, sweeps) would leak host memory
@functools.lru_cache(maxsize=32)
def _pipeline_callable(block_fn: Callable, mesh: Mesh, axis_name: str,
                       n_stages: int):
    """Cached jitted partial-manual pipeline over ``axis_name``.

    in_specs uses pytree-PREFIX specs, so one cache entry serves any
    stacked-params structure; cache key includes block_fn — pass a
    STABLE callable (a stored bound method, not a fresh lambda) or every
    call recompiles. jit is load-bearing: partial-manual shard_map
    cannot run eagerly; under an outer jit it inlines.
    """
    fn = functools.partial(spmd_pipeline, block_fn, axis_name=axis_name,
                           n_stages=n_stages)
    return jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        axis_names=frozenset({axis_name}),
        check_vma=False))


def pipeline_forward(block_fn: Callable, stacked_params, x, mesh: Mesh, *,
                     axis_name: str = "pipe", n_microbatches: int):
    """Full-array convenience wrapper — composes with DP/TP.

    stacked_params: pytree with leading dim [n_layers] (n_layers divisible
    by the pipe axis size); x: [batch, ...] (batch divisible by
    n_microbatches). Returns [batch, ...].

    Only ``axis_name`` is mapped manually; every OTHER mesh axis stays
    an auto (GSPMD) axis, so a (data × pipe × model) mesh runs the
    microbatch dim data-parallel and the within-block matmuls
    tensor-parallel with XLA-inserted collectives, while activations
    ride the pipe ring via ppermute — DP×TP×PP in one jitted step.
    """
    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    assert b % n_microbatches == 0, (b, n_microbatches)
    mb = b // n_microbatches
    xm = x.reshape((n_microbatches, mb) + x.shape[1:])
    out = _pipeline_callable(block_fn, mesh, axis_name,
                             n_stages)(stacked_params, xm)
    return out.reshape((b,) + out.shape[2:])
