"""Ulysses-style all-to-all sequence parallelism (net-new vs the
reference, which has no sequence parallelism — SURVEY.md §2.3/§5; the
second of the two first-class long-context layouts next to ring
attention).

Where ring attention rotates K/V blocks around the mesh, Ulysses
re-shards: an all-to-all swaps the sharded dim from SEQUENCE to HEADS,
every device then computes FULL-sequence attention for its head group
(any kernel — here the memory-routed dot_product_attention), and a
second all-to-all swaps back. Two collectives per layer, no online
softmax, requires heads % mesh == 0. On an ICI mesh the all-to-alls are
bandwidth-cheap (each device exchanges 1/n of its activations).
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = False,
                      segments=None):
    """Exact attention with sequence sharded over ``axis_name``.

    Per-shard q,k,v: [B, H, S_local, D] with H divisible by the axis
    size. Returns [B, H, S_local, D]. ``segments`` [B, S_local] are
    per-shard packed segment ids: heads re-shard but the sequence goes
    FULL per head group, so an all-gather rebuilds the global id row
    and the dense same-segment mask applies unchanged.
    """
    n = jax.lax.psum(1, axis_name)

    def seq_to_heads(x):
        # [B, H, S/n, D] -> all-to-all -> [B, H/n, S, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    if q.shape[1] % n:
        raise ValueError(
            f"ulysses needs heads ({q.shape[1]}) divisible by the "
            f"'{axis_name}' mesh size ({n})")
    from bigdl_tpu.nn.attention import dot_product_attention
    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    seg_full = None
    if segments is not None:
        seg_full = jax.lax.all_gather(segments.astype(jnp.int32),
                                      axis_name, axis=1, tiled=True)
    oh = dot_product_attention(qh, kh, vh, causal=causal,
                               segments=seg_full)
    return heads_to_seq(oh)


def ulysses_attention_sharded(q, k, v, mesh: Mesh, seq_axis: str = "seq",
                              *, causal: bool = False, segments=None):
    """Full-array convenience wrapper: shards S over ``seq_axis`` and
    runs Ulysses attention under shard_map. q,k,v: [B, H, S, D];
    ``segments`` [B, S] global packed ids, sharded alongside. Mesh
    axes other than ``seq_axis`` stay GSPMD-auto (composes with DP/TP);
    the wrapper is cached, so call it every forward."""
    from bigdl_tpu.parallel.mesh import seq_sharded_attention
    fn = seq_sharded_attention(ulysses_attention, mesh, seq_axis, causal,
                               segments is not None)
    return fn(q, k, v) if segments is None else fn(q, k, v, segments)
