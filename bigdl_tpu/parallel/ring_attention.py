"""Ring attention — exact sequence/context-parallel attention over an ICI
ring (net-new vs the reference, which has no sequence parallelism:
SURVEY.md §2.3/§5. Design follows the blockwise/ring-attention pattern:
K/V blocks rotate around the mesh axis via ``ppermute`` while each shard
keeps a running online-softmax accumulator, so memory is linear in the
LOCAL sequence length and comms overlap compute around the ring).

Use inside ``shard_map`` with the sequence dim sharded over ``axis_name``
(per-shard shapes [B, H, S_local, D]), or call :func:`ring_attention_sharded`
on full arrays and let it wrap the shard_map.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_accum(q, k, v, m, l, o, qpos, kpos, *, causal, scale,
                 seg_q=None, seg_k=None):
    """One K/V block of online-softmax attention.

    q [B,H,Sq,D]; k,v [B,H,Sk,D]; m,l [B,H,Sq]; o [B,H,Sq,D];
    qpos [Sq], kpos [Sk] global positions for causal masking;
    seg_q [B,Sq] / seg_k [B,Sk] packed segment ids (None = no packing)
    — cross-segment scores mask out exactly like the dense path's
    same-segment mask, so packed slabs ride the ring bit-faithfully.
    """
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    neg = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
    if causal:
        cmask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(cmask[None, None], scores, neg)
    if seg_q is not None:
        smask = seg_q[:, None, :, None] == seg_k[:, None, None, :]
        scores = jnp.where(smask, scores, neg)
    smax = jnp.max(scores, axis=-1)                      # [B,H,Sq]
    m_new = jnp.maximum(m, smax)
    # rows with everything masked keep m_new == neg; exp underflows to 0
    p = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False,
                   segments=None):
    """Exact attention with sequence sharded over ``axis_name``.

    Per-shard q,k,v: [B, H, S_local, D]. Returns [B, H, S_local, D].
    ``segments`` [B, S_local] are per-shard packed segment ids — the
    key-side ids rotate around the ring WITH their K/V block, so every
    shard masks cross-segment scores against the block it currently
    holds (bit-faithful to the dense same-segment mask).
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    scale = 1.0 / math.sqrt(d)
    dtype = jnp.promote_types(q.dtype, jnp.float32)
    q32, k0, v0 = q.astype(dtype), k.astype(dtype), v.astype(dtype)
    has_seg = segments is not None
    seg0 = (segments.astype(jnp.int32) if has_seg
            else jnp.zeros((b, s_loc), jnp.int32))

    qpos = my * s_loc + jnp.arange(s_loc)
    neg = jnp.asarray(jnp.finfo(dtype).min, dtype)
    m0 = jnp.full((b, h, s_loc), neg, dtype)
    l0 = jnp.zeros((b, h, s_loc), dtype)
    o0 = jnp.zeros((b, h, s_loc, d), dtype)
    # the accumulators (and the dummy all-zero segment carry when
    # packing is off) become shard-varying inside the scan; mark the
    # (constant) initial values as such for the vma type check
    varying = (m0, l0, o0) if has_seg else (m0, l0, o0, seg0)
    if hasattr(jax.lax, "pcast"):
        varying = jax.lax.pcast(varying, (axis_name,), to="varying")
    elif hasattr(jax.lax, "pvary"):
        varying = jax.lax.pvary(varying, (axis_name,))
    if has_seg:
        m0, l0, o0 = varying
    else:
        m0, l0, o0, seg0 = varying
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        k_blk, v_blk, seg_blk, m, l, o = carry
        src = (my - t) % n  # which shard's block we currently hold
        kpos = src * s_loc + jnp.arange(s_loc)
        m, l, o = _block_accum(
            q32, k_blk, v_blk, m, l, o, qpos, kpos,
            causal=causal, scale=scale,
            seg_q=segments if has_seg else None,
            seg_k=seg_blk if has_seg else None)
        # rotate AFTER consuming; skip the final (wasted) hop
        k_nxt, v_nxt, seg_nxt = jax.lax.cond(
            t < n - 1,
            lambda kv: jax.lax.ppermute(kv, axis_name, perm),
            lambda kv: kv,
            (k_blk, v_blk, seg_blk))
        return (k_nxt, v_nxt, seg_nxt, m, l, o), None

    (k_f, v_f, seg_f, m, l, o), _ = jax.lax.scan(
        step, (k0, v0, seg0, m0, l0, o0), jnp.arange(n))
    # fully-masked rows (l == 0) -> zeros, not NaN
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = o / safe_l[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, seq_axis: str = "seq",
                           *, causal: bool = False, segments=None):
    """Full-array convenience wrapper: shards S over ``seq_axis`` and runs
    ring attention under shard_map. q,k,v: [B, H, S, D] (global);
    ``segments`` [B, S] global packed ids, sharded alongside. Mesh
    axes other than ``seq_axis`` stay GSPMD-auto (composes with DP/TP);
    the wrapper is cached, so call it every forward."""
    from bigdl_tpu.parallel.mesh import seq_sharded_attention
    fn = seq_sharded_attention(ring_attention, mesh, seq_axis, causal,
                               segments is not None)
    return fn(q, k, v) if segments is None else fn(q, k, v, segments)
