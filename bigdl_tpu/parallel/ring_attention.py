"""Ring attention — exact sequence/context-parallel attention over an ICI
ring (net-new vs the reference, which has no sequence parallelism:
SURVEY.md §2.3/§5. Design follows the blockwise/ring-attention pattern:
K/V blocks rotate around the mesh axis via ``ppermute`` while each shard
keeps a running online-softmax accumulator, so memory is linear in the
LOCAL sequence length and comms overlap compute around the ring).

Use inside ``shard_map`` with the sequence dim sharded over ``axis_name``
(per-shard shapes [B, H, S_local, D]), or call :func:`ring_attention_sharded`
on full arrays and let it wrap the shard_map.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_accum(q, k, v, m, l, o, qpos, kpos, *, causal, scale):
    """One K/V block of online-softmax attention.

    q [B,H,Sq,D]; k,v [B,H,Sk,D]; m,l [B,H,Sq]; o [B,H,Sq,D];
    qpos [Sq], kpos [Sk] global positions for causal masking.
    """
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    neg = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
    if causal:
        cmask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(cmask[None, None], scores, neg)
    smax = jnp.max(scores, axis=-1)                      # [B,H,Sq]
    m_new = jnp.maximum(m, smax)
    # rows with everything masked keep m_new == neg; exp underflows to 0
    p = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False):
    """Exact attention with sequence sharded over ``axis_name``.

    Per-shard q,k,v: [B, H, S_local, D]. Returns [B, H, S_local, D].
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    scale = 1.0 / math.sqrt(d)
    dtype = jnp.promote_types(q.dtype, jnp.float32)
    q32, k0, v0 = q.astype(dtype), k.astype(dtype), v.astype(dtype)

    qpos = my * s_loc + jnp.arange(s_loc)
    neg = jnp.asarray(jnp.finfo(dtype).min, dtype)
    m0 = jnp.full((b, h, s_loc), neg, dtype)
    l0 = jnp.zeros((b, h, s_loc), dtype)
    o0 = jnp.zeros((b, h, s_loc, d), dtype)
    # the accumulators become shard-varying inside the scan; mark the
    # (constant) initial values as such for the vma type check
    if hasattr(jax.lax, "pcast"):
        m0, l0, o0 = jax.lax.pcast((m0, l0, o0), (axis_name,),
                                   to="varying")
    elif hasattr(jax.lax, "pvary"):
        m0, l0, o0 = jax.lax.pvary((m0, l0, o0), (axis_name,))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        k_blk, v_blk, m, l, o = carry
        src = (my - t) % n  # which shard's block we currently hold
        kpos = src * s_loc + jnp.arange(s_loc)
        m, l, o = _block_accum(q32, k_blk, v_blk, m, l, o, qpos, kpos,
                               causal=causal, scale=scale)
        # rotate AFTER consuming; skip the final (wasted) hop
        k_nxt, v_nxt = jax.lax.cond(
            t < n - 1,
            lambda kv: jax.lax.ppermute(kv, axis_name, perm),
            lambda kv: kv,
            (k_blk, v_blk))
        return (k_nxt, v_nxt, m, l, o), None

    (k_f, v_f, m, l, o), _ = jax.lax.scan(
        step, (k0, v0, m0, l0, o0), jnp.arange(n))
    # fully-masked rows (l == 0) -> zeros, not NaN
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = o / safe_l[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, seq_axis: str = "seq",
                           *, causal: bool = False):
    """Full-array convenience wrapper: shards S over ``seq_axis`` and runs
    ring attention under shard_map. q,k,v: [B, H, S, D] (global). Mesh
    axes other than ``seq_axis`` stay GSPMD-auto (composes with DP/TP);
    the wrapper is cached, so call it every forward."""
    from bigdl_tpu.parallel.mesh import seq_sharded_attention
    return seq_sharded_attention(ring_attention, mesh, seq_axis,
                                 causal)(q, k, v)
