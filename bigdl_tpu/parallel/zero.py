"""ZeRO-2/3 weight-update sharding over the data axis (GSPMD-native).

The reference's partitioned parameter server (AllReduceParameter.scala:
214-303: each node owns 1/n of the flattened parameter space, aggregates
its slice, runs the OptimMethod on it, and all-gathers the updated
weights) is exactly what "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" (arXiv:2004.13336) gives TPUs through
sharding annotations alone — and the RDMA thesis (arXiv:1805.08430)
says the win only lands when the collectives hide behind compute. Both
are honored here without a single hand-written collective:

- **stage 1** — optimizer state sharded at rest; gradients stay
  all-reduced (the repo's original ``zero1`` flag).
- **stage 2** — gradients are *constrained* to the sharded layout right
  where ``jax.grad`` produces them, so XLA turns the gradient all-reduce
  into a reduce-scatter (on CPU: all-reduce + dynamic-slice — same
  math, same bytes-per-chip); each replica updates only its 1/n
  gradient + optimizer-state shard and ONE params all-gather follows
  the update.
- **stage 3** — additionally keeps params sharded at rest; every
  layer's weights are all-gathered just-in-time at their use site
  inside the forward/backward (XLA places the gather next to the
  consuming op, so peak live memory is one layer's worth, and the
  gathered copy is discarded — the ``jax.remat``-friendly
  gather-discard regime).

Inside the windowed step driver (``Optimizer.set_steps_per_sync``) the
donated ``lax.scan`` carry holds the *sharded* optimizer state, and the
constraints sit inside the scan body — XLA is free to overlap step
N+1's backward with step N's reduce-scatter, and no per-layer gather
escapes to the host boundary (asserted via :func:`collective_counts`).

Exactness is the contract: the update math is elementwise over shards,
so stage-0 vs stage-1/2/3 differ only by collective reduction order
(float tolerance, bounded in the multichip dryrun), never semantics.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.parallel.tp import Rules, _path_str, put_global, spec_for

# per-chip resident bytes, set whenever a training run lays out its
# state (Optimizer._optimize_impl / tools.perf --zero / bench ZERO row):
# the observable proof of the n-fold ZeRO memory reduction that makes
# larger-than-chip models a supported scenario
_OPT_BYTES = telemetry.gauge(
    "train/memory/opt_state_bytes_per_chip",
    "bytes of optimizer state resident per chip after sharding")
_PARAM_BYTES = telemetry.gauge(
    "train/memory/params_bytes_per_chip",
    "bytes of parameters resident per chip after sharding")


@dataclasses.dataclass(frozen=True)
class ZeroConfig:
    """Weight-update sharding policy over the ``data`` mesh axis.

    ``stage`` — 0: off (pure DP replication); 1: optimizer state
    sharded; 2: + gradients reduce-scattered and updated per-shard,
    one params all-gather per step; 3: + params sharded at rest,
    per-layer just-in-time gathers inside forward/backward.
    ``data_axis`` — the mesh axis to shard over (the batch axis).
    """

    stage: int = 2
    data_axis: str = "data"

    def __post_init__(self):
        if self.stage not in (0, 1, 2, 3):
            raise ValueError(
                f"ZeroConfig.stage must be 0, 1, 2 or 3, got {self.stage}")

    def active_on(self, mesh: Optional[Mesh]) -> bool:
        """True when the policy does anything on ``mesh``: a real mesh
        whose data axis actually splits, and a stage above 0."""
        return (self.stage > 0 and mesh is not None
                and mesh.shape.get(self.data_axis, 1) > 1)


def extend_spec(base: P, shape, ndev: int, data_axis: str) -> P:
    """``base`` (the TP/EP rule spec, or ``P()``) with the FIRST free,
    divisibly-sized dim additionally sharded over ``data_axis`` — the
    FSDP composition rule: ZeRO takes whatever dims tensor parallelism
    left unsharded. Leaves with no qualifying dim (scalars, tiny
    biases) keep ``base`` — still an explicit spec, never unannotated.
    """
    if ndev <= 1 or not shape:
        return base
    entries = list(base) + [None] * (len(shape) - len(base))
    used = set()
    for e in entries:
        if e is not None:
            used.update((e,) if isinstance(e, str) else tuple(e))
    if data_axis in used:
        return base  # rules already consume the axis for this leaf
    for d, e in enumerate(entries):
        if e is None and shape[d] > 0 and shape[d] % ndev == 0:
            entries[d] = data_axis
            return P(*entries)
    return base


def tree_zero_specs(tree, mesh: Mesh, config: ZeroConfig,
                    rules: Optional[Rules] = None):
    """Pytree of PartitionSpecs for a params-shaped (or optimizer-state)
    tree under ``config``: every leaf gets an EXPLICIT spec — sharded
    where a dim divides the data axis, the TP-rule (or replicated) base
    otherwise. Shape-only: works on live arrays, tracers and
    ``jax.eval_shape`` structs alike."""
    ndev = mesh.shape.get(config.data_axis, 1)

    def leaf_spec(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        base = spec_for(_path_str(path), len(shape), rules) if rules \
            else P()
        return extend_spec(base, shape, ndev, config.data_axis)

    return jax.tree_util.tree_map_with_path(leaf_spec, tree)


def tree_base_specs(tree, mesh: Mesh, rules: Optional[Rules] = None):
    """The stage-0 layout: TP-rule specs where rules match, replicated
    everywhere else — what stage-2 gathers params back to after the
    sharded update."""

    def leaf_spec(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        return spec_for(_path_str(path), len(shape), rules) if rules \
            else P()

    return jax.tree_util.tree_map_with_path(leaf_spec, tree)


def shard_zero_tree(tree, mesh: Mesh, config: ZeroConfig,
                    rules: Optional[Rules] = None):
    """Place a host tree on ``mesh`` in its ZeRO layout (multi-host
    safe). Used for the at-rest state: optimizer state at stage >= 1,
    params at stage 3."""
    specs = tree_zero_specs(tree, mesh, config, rules)
    return jax.tree.map(
        lambda leaf, spec: put_global(leaf, NamedSharding(mesh, spec)),
        tree, specs)


def place_zero_params(tree, mesh: Mesh, config: Optional[ZeroConfig],
                      rules: Optional[Rules] = None):
    """Params' at-rest placement under ``config``: sharded over the
    data axis only at stage 3, else the TP-rule layout when ``rules``
    are given, else replicated."""
    if config is not None and config.stage == 3:
        return shard_zero_tree(tree, mesh, config, rules)
    if rules is not None:
        from bigdl_tpu.parallel.tp import shard_params
        return shard_params(tree, mesh, rules)
    return jax.tree.map(
        lambda leaf: put_global(leaf, NamedSharding(mesh, P())), tree)


def place_zero_opt_state(tree, mesh: Mesh, config: Optional[ZeroConfig],
                         rules: Optional[Rules] = None):
    """Optimizer state's at-rest placement under ``config``: sharded at
    any stage >= 1, else the TP-rule layout, else replicated. The
    sharded leg is timed into ``parallel/tp/shard_opt_state_s`` under a
    ``parallel/shard_opt_state`` span — the one instrumented entry
    point for every harness that lays ZeRO state out."""
    if config is not None and config.stage >= 1:
        import time as _time
        t0 = _time.perf_counter()
        with telemetry.span("parallel/shard_opt_state",
                            stage=config.stage):
            out = shard_zero_tree(tree, mesh, config, rules)
        telemetry.histogram("parallel/tp/shard_opt_state_s").observe(
            _time.perf_counter() - t0)
        return out
    if rules is not None:
        from bigdl_tpu.parallel.tp import shard_params
        return shard_params(tree, mesh, rules)
    return jax.tree.map(
        lambda leaf: put_global(leaf, NamedSharding(mesh, P())), tree)


def place_zero_state(params, opt_state, mesh: Mesh,
                     config: Optional[ZeroConfig],
                     rules: Optional[Rules] = None):
    """Both halves of the at-rest layout in one call — the placement
    dance every training harness (Optimizer, bench, perf, the dryrun)
    otherwise re-implements."""
    return (place_zero_params(params, mesh, config, rules),
            place_zero_opt_state(opt_state, mesh, config, rules))


def constrain_zero(tree, mesh: Mesh, config: ZeroConfig,
                   rules: Optional[Rules] = None):
    """``with_sharding_constraint`` every leaf to its ZeRO spec, INSIDE
    a jitted computation. On gradients this is the reduce-scatter
    point; on fresh optimizer state it pins the sharded layout so
    inferred jit out-shardings can never silently re-replicate a shard
    after the first donated update."""
    specs = tree_zero_specs(tree, mesh, config, rules)
    return jax.tree.map(
        lambda leaf, spec: jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, spec)), tree, specs)


def constrain_base(tree, mesh: Mesh, rules: Optional[Rules] = None):
    """Constrain every leaf back to the stage-0 layout (replicated, or
    the TP rules) — the single params all-gather stage 2 performs after
    its sharded update."""
    specs = tree_base_specs(tree, mesh, rules)
    return jax.tree.map(
        lambda leaf, spec: jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, spec)), tree, specs)


def spec_to_entries(spec) -> list:
    """JSON-able form of a ``PartitionSpec`` — one entry per dim:
    ``None`` (unsharded), an axis name, or a list of axis names. The
    wire form the elastic checkpoint MANIFEST records per leaf so a
    resume onto a *different* mesh can reassemble the global array
    from its parts (``elastic.checkpoint``)."""
    if spec is None:
        return []
    out = []
    for e in tuple(spec):
        if e is None or isinstance(e, str):
            out.append(e)
        else:
            out.append(list(e))
    return out


def entries_to_spec(entries) -> P:
    """Inverse of :func:`spec_to_entries`."""
    return P(*[e if e is None or isinstance(e, str) else tuple(e)
               for e in (entries or [])])


def tree_bytes_per_chip(tree, floating_as=None) -> int:
    """Resident bytes per chip for a (possibly sharded) pytree: each
    leaf contributes its per-device shard size — ``sharding.shard_shape``
    when the leaf carries one (live arrays and sharded
    ``jax.eval_shape`` structs), its full shape otherwise. This is what
    the ``train/memory/*_bytes_per_chip`` gauges report.

    ``floating_as`` prices every floating leaf at that dtype instead of
    its own — the "what would this layout cost at f32" counterfactual
    the precision gauges report as the before number."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        if floating_as is not None and np.issubdtype(dtype, np.floating):
            dtype = np.dtype(floating_as)
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            shape = sharding.shard_shape(shape)
        total += int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    return total


def record_memory_gauges(params, opt_state) -> Dict[str, int]:
    """Set the per-chip memory gauges from the placed training state
    and return the two byte counts (``params``, ``opt_state``)."""
    pb = tree_bytes_per_chip(params)
    ob = tree_bytes_per_chip(opt_state)
    _PARAM_BYTES.set(pb)
    _OPT_BYTES.set(ob)
    return {"params_bytes_per_chip": pb, "opt_state_bytes_per_chip": ob}


# Deprecated shims: the HLO regex parsing that used to live here is now
# the structural parser in ``bigdl_tpu.analysis.hlo`` (one parser for
# these counters, the windowed-contract test assertions AND the
# `check --programs` verifier — including the tuple-typed async -start
# collective forms real TPU schedules emit). Imported names stay valid.
_COLLECTIVES = ("all-gather", "reduce-scatter", "all-reduce",
                "collective-permute", "all-to-all", "dynamic-slice")


def collective_counts(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Count collective ops in compiled-HLO text, split into the ENTRY
    computation vs everything else (scan/while bodies, fusions).

    ``{"all-gather": {"total": n, "entry": m}, ...}`` — the windowed
    ZeRO contract is ``entry == 0`` for the gather/reduce collectives:
    they live INSIDE the scanned window where XLA can overlap them with
    the neighbouring steps' compute, never at the host dispatch
    boundary. ``dynamic-slice`` (not itself a collective — it also
    serves ordinary indexing) is counted because XLA CPU lowers
    reduce-scatter to all-reduce + dynamic-slice — on that backend the
    scatter evidence is the pair, not the fused op.

    Deprecated shim: delegates to
    :func:`bigdl_tpu.analysis.hlo.collective_counts` (the structural
    parser); new code should call that directly."""
    from bigdl_tpu.analysis.hlo import collective_counts as _counts
    return _counts(hlo_text)


def window_collectives(compiled) -> Dict[str, Dict[str, int]]:
    """:func:`collective_counts` over a compiled jit program (the
    object ``jax.jit(f).lower(...).compile()`` returns). Deprecated
    shim over :mod:`bigdl_tpu.analysis.hlo`."""
    return collective_counts(compiled.as_text())


def reduce_scatter_evidence(counts: Dict[str, Dict[str, int]]) -> bool:
    """True when the program reduce-scatters gradients: a literal
    ``reduce-scatter`` op (TPU), or the CPU lowering's
    all-reduce + dynamic-slice pair. (Shared implementation:
    :func:`bigdl_tpu.analysis.hlo.reduce_scatter_evidence`.)"""
    from bigdl_tpu.analysis.hlo import reduce_scatter_evidence as _ev
    return _ev(counts)
