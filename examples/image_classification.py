"""Image-classification predict example — load a trained model and label a
folder of images (example/imageclassification/ImagePredictor.scala:32-76:
load model → decode/crop/normalize images → DLClassifierModel transform →
print imageName, predict).

    python examples/image_classification.py -f /imagenet/val --model snap
    python examples/image_classification.py --synthetic 8   # no data needed
"""
from __future__ import annotations

import argparse
import os


IMAGE_SIZE = 224
_IMG_EXTS = (".jpg", ".jpeg", ".png")


def scan_images(folder: str):
    """Recursive, case-insensitive image scan (LocalImageFiles.readPaths
    — ImageNet val names are uppercase .JPEG on disk)."""
    paths = []
    for root, _, files in os.walk(folder):
        for fn in files:
            if fn.lower().endswith(_IMG_EXTS):
                paths.append(os.path.join(root, fn))
    return sorted(paths)


def decode_batch(paths):
    """Decode + center-crop 224 + ImageNet-normalize one batch of paths
    (MlUtils.scala imagesLoad + the transformer chain BytesToBGRImg ->
    BGRImgCropper -> BGRImgNormalizer). Batched so an ImageNet-sized
    folder never materializes in host memory at once."""
    import numpy as np

    from bigdl_tpu.dataset import decode_image
    from bigdl_tpu.dataset.imagenet import IMAGENET_MEAN, IMAGENET_STD

    mean = np.asarray(IMAGENET_MEAN, np.float32).reshape(3, 1, 1)
    std = np.asarray(IMAGENET_STD, np.float32).reshape(3, 1, 1)
    imgs = []
    for p in paths:
        img = decode_image(p, scale=256)
        h, w = img.shape[:2]
        oy, ox = (h - IMAGE_SIZE) // 2, (w - IMAGE_SIZE) // 2
        chw = img[oy:oy + IMAGE_SIZE, ox:ox + IMAGE_SIZE] \
            .transpose(2, 0, 1).astype(np.float32)
        imgs.append((chw - mean) / std)
    return np.stack(imgs)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Predict image classes with a trained model")
    ap.add_argument("-f", "--folder", default="./",
                    help="folder of images to label")
    ap.add_argument("--model", default=None, help="model snapshot")
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    ap.add_argument("--classNum", type=int, default=1000)
    ap.add_argument("--showNum", type=int, default=100,
                    help="print at most this many predictions")
    ap.add_argument("--synthetic", type=int, default=0, metavar="N",
                    help="predict N random images instead of -f data")
    args = ap.parse_args(argv)

    import numpy as np

    from bigdl_tpu.ml import DLClassifierModel

    if args.model:
        from bigdl_tpu.utils.serialization import load_module
        model = load_module(args.model)
    else:
        from bigdl_tpu.models.inception import Inception_v1_NoAuxClassifier
        model = Inception_v1_NoAuxClassifier(args.classNum)

    clf = DLClassifierModel(model, batch_size=args.batchSize)
    if args.synthetic:
        rng = np.random.RandomState(0)
        names = [f"synthetic_{i}.jpg" for i in range(args.synthetic)]
        imgs = rng.rand(args.synthetic, 3, IMAGE_SIZE,
                        IMAGE_SIZE).astype(np.float32)
        out = list(zip(names, clf.predict(imgs).tolist()))
    else:
        names = scan_images(args.folder)
        if not names:
            raise SystemExit(f"no images found under {args.folder}")
        out = []
        for i in range(0, len(names), args.batchSize):
            chunk = names[i:i + args.batchSize]
            out.extend(zip(chunk, clf.predict(decode_batch(chunk)).tolist()))

    for name, pred in out[:args.showNum]:
        print(f"{os.path.basename(str(name))}: {pred}")
    return out


if __name__ == "__main__":
    main()
