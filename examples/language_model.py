"""PTB word-level language model — the stacked-LSTM recipe
(example/languagemodel/PTBWordLM.scala:40-120: PTBModel with dropout,
Adagrad, TimeDistributed CrossEntropy, per-epoch validation
perplexity).

    python examples/language_model.py -f /data/ptb   # train/valid.txt
    python examples/language_model.py --synthetic 4000
"""
from __future__ import annotations

import argparse
import os


def main(argv=None):
    ap = argparse.ArgumentParser(description="PTB word LM (PTBWordLM)")
    ap.add_argument("-f", "--folder", default="./",
                    help="directory with train.txt / valid.txt")
    ap.add_argument("-b", "--batchSize", type=int, default=20)
    ap.add_argument("-e", "--maxEpoch", type=int, default=2)
    ap.add_argument("--vocabSize", type=int, default=10000)
    ap.add_argument("--hiddenSize", type=int, default=200)
    ap.add_argument("--numLayers", type=int, default=2)
    ap.add_argument("--numSteps", type=int, default=20)
    ap.add_argument("--keepProb", type=float, default=2.0,
                    help="<1 enables dropout (PTBModel.scala keepProb)")
    ap.add_argument("--learningRate", type=float, default=0.1)
    ap.add_argument("--maxIterations", type=int, default=None)
    ap.add_argument("--synthetic", type=int, default=0, metavar="N",
                    help="train on an N-token synthetic stream")
    args = ap.parse_args(argv)

    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import (DataSet, Sample, SampleToMiniBatch,
                                   load_ptb, ptb_arrays)
    from bigdl_tpu.models import PTBModel
    from bigdl_tpu.optim import (Adagrad, LocalOptimizer, Loss,
                                 every_epoch, max_epoch, max_iteration)

    if args.synthetic:
        rng = np.random.RandomState(0)
        vocab = min(args.vocabSize, 50)
        # learnable synthetic stream: a noisy repeating pattern; the val
        # split is a FRESH continuation (same pattern, different noise
        # realization) so validation measures generalization, not
        # memorization
        n = args.synthetic + 2000
        base = np.tile(np.arange(1, vocab + 1), n // vocab + 1)[:n]
        noise = rng.randint(1, vocab + 1, n)
        keep = rng.rand(n) < 0.9
        full = np.where(keep, base, noise).astype(np.float32)
        stream, val_stream = full[:args.synthetic], full[args.synthetic:]
    else:
        splits, d = load_ptb(
            os.path.join(args.folder, "train.txt"),
            vocab_size=args.vocabSize,
            valid_path=os.path.join(args.folder, "valid.txt"))
        stream, vocab = splits["train"], d.vocab_size()
        val_stream = splits.get("valid")
        if val_stream is None:
            print("warning: no valid.txt found — skipping validation")

    def to_ds(token_stream):
        x, y = ptb_arrays(token_stream, args.batchSize, args.numSteps)
        samples = [Sample(x[i], y[i]) for i in range(len(x))]
        return DataSet.array(samples).transform(
            SampleToMiniBatch(args.batchSize))

    model = PTBModel(vocab, args.hiddenSize, vocab,
                     num_layers=args.numLayers, keep_prob=args.keepProb)
    # size_average=True -> the loss is per-TOKEN cross entropy, so
    # exp(loss) below is true perplexity (the reference trains on the
    # step-summed form, PTBWordLM.scala:91; the gradient direction is
    # identical, only the scale folds into the learning rate)
    crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion(),
                                       size_average=True)
    opt = LocalOptimizer(model, to_ds(stream), crit,
                         batch_size=args.batchSize)
    opt.set_optim_method(Adagrad(learning_rate=args.learningRate))
    if val_stream is not None:
        opt.set_validation(every_epoch(), to_ds(val_stream), [Loss(crit)])
    if args.maxIterations:
        opt.set_end_when(max_iteration(args.maxIterations))
    else:
        opt.set_end_when(max_epoch(args.maxEpoch))
    opt.optimize()
    loss = opt.driver_state["Loss"]
    val = opt.driver_state.get("score")
    print(f"train loss {loss:.4f} perplexity {np.exp(loss):.2f}")
    if val is not None:
        print(f"valid loss {val:.4f} perplexity {np.exp(val):.2f}")
    return opt.driver_state


if __name__ == "__main__":
    main()
