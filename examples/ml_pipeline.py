"""ML-pipeline example — train LeNet through the estimator API
(example/MLPipeline/DLClassifierLeNet.scala: an MNIST LeNet fitted and
served entirely through the DLClassifier estimator/transformer pair).

    python examples/ml_pipeline.py -f /path/to/mnist
    python examples/ml_pipeline.py --synthetic 256   # no data needed
"""
from __future__ import annotations

import argparse


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Train + serve LeNet via the DLClassifier estimator")
    ap.add_argument("-f", "--folder", default="./")
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    ap.add_argument("-e", "--maxEpoch", type=int, default=4)
    ap.add_argument("-r", "--learningRate", type=float, default=0.05)
    ap.add_argument("--synthetic", type=int, default=0, metavar="N")
    args = ap.parse_args(argv)

    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.ml import DLClassifier
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.models._cli import mnist_arrays

    if args.synthetic:
        # separable synthetic digits: class decides which quadrant lights
        rng = np.random.RandomState(0)
        n = args.synthetic
        ys = rng.randint(1, 3, n).astype(np.float32)
        xs = rng.rand(n, 1, 28, 28).astype(np.float32) * 0.1
        for i in range(n):
            if ys[i] == 1:
                xs[i, 0, :14, :14] += 0.9
            else:
                xs[i, 0, 14:, 14:] += 0.9
    else:
        xs, ys = mnist_arrays(args.folder, True, 0)

    clf = DLClassifier(LeNet5(10), nn.ClassNLLCriterion(),
                       batch_size=args.batchSize,
                       max_epoch=args.maxEpoch,
                       learning_rate=args.learningRate)
    fitted = clf.fit(xs, ys)
    acc = fitted.score(xs, ys)
    print(f"train accuracy: {acc:.4f}")
    preds = fitted.predict(xs[:8])
    print("sample predictions:", preds.tolist())
    return acc


if __name__ == "__main__":
    main()
