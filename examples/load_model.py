"""Model validation example (reference: example/loadmodel/ModelValidator.scala
— load a BigDL/Caffe/Torch/TF model and evaluate Top1/Top5 on a labeled
image folder).

    python examples/load_model.py --model-type caffe \
        --def net.prototxt --model net.caffemodel -f /data/val
    python examples/load_model.py --model-type bigdl --model saved_dir \
        --synthetic 64 --classes 10 --size 32
"""
from __future__ import annotations

import argparse


def load(model_type, model_path, def_path=None):
    if model_type == "bigdl":
        from bigdl_tpu.utils.serialization import load_module
        return load_module(model_path)
    if model_type == "caffe":
        from bigdl_tpu.utils.caffe import load_caffe
        return load_caffe(def_path=def_path, model_path=model_path)
    if model_type == "torch":
        from bigdl_tpu.utils.torch_file import load_torch_model
        return load_torch_model(model_path)
    if model_type in ("tf", "tensorflow"):
        from bigdl_tpu.utils.tf_loader import load_tf_graph
        return load_tf_graph(model_path)
    raise ValueError(model_type)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model-type", required=True,
                    choices=["bigdl", "caffe", "torch", "tf", "tensorflow"])
    ap.add_argument("--model", required=True)
    ap.add_argument("--def", dest="def_path", default=None)
    ap.add_argument("-f", "--folder", default=None,
                    help="labeled image folder (class subdirs)")
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    ap.add_argument("--crop", type=int, default=224)
    ap.add_argument("--scale", type=int, default=256)
    ap.add_argument("--synthetic", type=int, default=0)
    ap.add_argument("--classes", type=int, default=1000)
    ap.add_argument("--size", type=int, default=224)
    args = ap.parse_args(argv)

    import numpy as np

    from bigdl_tpu.optim import Evaluator, Top1Accuracy, Top5Accuracy

    model = load(args.model_type, args.model, args.def_path).evaluate()

    if args.synthetic:
        from bigdl_tpu.dataset import DataSet, Sample
        rng = np.random.RandomState(0)
        samples = [Sample(rng.rand(3, args.size, args.size)
                          .astype(np.float32),
                          float(rng.randint(1, args.classes + 1)))
                   for _ in range(args.synthetic)]
        ds = DataSet.array(samples)
    else:
        from bigdl_tpu.dataset import ImageFolderDataSet
        ds = ImageFolderDataSet(args.folder, batch_size=args.batchSize,
                                crop=args.crop, scale=args.scale)

    results = Evaluator(model).test(
        ds, [Top1Accuracy(), Top5Accuracy()], batch_size=args.batchSize)
    for name, r in results.items():
        print(f"{name}: {r}")
    return results


if __name__ == "__main__":
    main()
