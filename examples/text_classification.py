"""Text classification example (reference: example/textclassification —
GloVe-embedding + CNN over 20-newsgroups; TextClassifier.scala).

Pipeline: SentenceTokenizer -> Dictionary -> index sequences ->
LookupTable embedding -> TemporalConvolution -> max-over-time pooling ->
Linear -> LogSoftMax.

    python examples/text_classification.py --synthetic 400
    python examples/text_classification.py -f /data/20news --classes 20
"""
from __future__ import annotations

import argparse
import os


def encode_text_ids(tokens, dictionary, seq_len: int):
    """tokenize->index->truncate->pad encoding shared by training and the
    UDF server; pads with the last id (or the unk index when empty)."""
    import numpy as np
    V = dictionary.vocab_size()
    ids = [dictionary.get_index(w) for w in tokens][:seq_len]
    ids += [ids[-1] if ids else V] * (seq_len - len(ids))
    return np.asarray(ids, np.float32)


def build_model(vocab_size: int, embed_dim: int, class_num: int):
    import bigdl_tpu.nn as nn
    m = nn.Sequential()
    m.add(nn.LookupTable(vocab_size, embed_dim))          # (B,T,E)
    m.add(nn.TemporalConvolution(embed_dim, 128, 5))      # (B,T-4,128)
    m.add(nn.ReLU())
    m.add(nn.Max(2, 3))                                   # max over time
    m.add(nn.Linear(128, class_num))
    m.add(nn.LogSoftMax())
    return m


def synthetic_corpus(n, classes, rng):
    """Class-correlated word streams: class c prefers tokens c*40..c*40+39."""
    texts, labels = [], []
    for i in range(n):
        c = i % classes
        base = ["w%d" % (c * 40 + int(v)) for v in rng.randint(0, 40, 30)]
        noise = ["w%d" % int(v) for v in rng.randint(0, classes * 40, 10)]
        words = list(rng.permutation(base + noise))
        texts.append(" ".join(words))
        labels.append(float(c + 1))
    return texts, labels


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-f", "--folder", default=None,
                    help="folder of <class>/<file>.txt documents")
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--synthetic", type=int, default=0)
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    ap.add_argument("-e", "--maxEpoch", type=int, default=5)
    ap.add_argument("--vocabSize", type=int, default=5000)
    ap.add_argument("--seqLen", type=int, default=40)
    ap.add_argument("--embedDim", type=int, default=32)
    args = ap.parse_args(argv)

    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import (DataSet, Dictionary, Sample,
                                   SampleToMiniBatch, tokenize)
    from bigdl_tpu.optim import (LocalOptimizer, SGD, Top1Accuracy,
                                 every_epoch, max_epoch)

    rng = np.random.RandomState(0)
    if args.synthetic:
        texts, labels = synthetic_corpus(args.synthetic, args.classes, rng)
    else:
        texts, labels = [], []
        classes = sorted(d for d in os.listdir(args.folder)
                         if os.path.isdir(os.path.join(args.folder, d)))
        for ci, cls in enumerate(classes):
            cdir = os.path.join(args.folder, cls)
            for fn in sorted(os.listdir(cdir)):
                with open(os.path.join(cdir, fn), errors="replace") as f:
                    texts.append(f.read())
                labels.append(float(ci + 1))
        args.classes = len(classes)

    token_lists = [tokenize(t) for t in texts]
    d = Dictionary(token_lists, vocab_size=args.vocabSize)
    V = d.vocab_size()

    X = np.stack([encode_text_ids(t, d, args.seqLen)
                  for t in token_lists])
    y = np.asarray(labels, np.float32)
    perm = rng.permutation(len(X))
    X, y = X[perm], y[perm]
    n_val = max(1, len(X) // 5)
    ds = DataSet.array([Sample(x, t) for x, t in
                        zip(X[n_val:], y[n_val:])]) \
        .transform(SampleToMiniBatch(args.batchSize))
    val = DataSet.array([Sample(x, t) for x, t in
                         zip(X[:n_val], y[:n_val])])

    model = build_model(V + 1, args.embedDim, args.classes)
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                         batch_size=args.batchSize)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_epoch(args.maxEpoch))
    opt.set_validation(every_epoch(), val, [Top1Accuracy()])
    opt.optimize()
    print(f"final loss {opt.driver_state['Loss']:.4f} "
          f"val score {opt.driver_state.get('score', float('nan')):.4f}")
    return opt.driver_state


if __name__ == "__main__":
    main()
