"""Pre-flight diagnostics walkthrough: a deliberately mis-wired model,
shown failing twice — first the OLD way (the raw error XLA tracing
produces, deep in framework internals, naming no layer), then the NEW
way (``Module.check`` / ``analysis.check_module``: a millisecond
eval_shape walk that names the exact offending layer path before any
compilation is attempted).

    python examples/miswired_model.py

The model: a CIFAR-style conv stack whose classifier head was copied
from an MNIST recipe — ``Linear(1568, 10)`` where the flattened conv
output is really 2048 wide. A classic wiring slip: every shape is
plausible, nothing fails until the matmul deep inside the traced step.
"""
from __future__ import annotations

import argparse

import numpy as np


def build_miswired():
    import bigdl_tpu.nn as nn

    return (nn.Sequential()
            .add(nn.SpatialConvolution(3, 32, 5, 5, 1, 1, 2, 2))
            .add(nn.ReLU())
            .add(nn.SpatialMaxPooling(2, 2, 2, 2))
            .add(nn.SpatialConvolution(32, 32, 5, 5, 1, 1, 2, 2))
            .add(nn.ReLU())
            .add(nn.SpatialMaxPooling(2, 2, 2, 2))
            .add(nn.Reshape((32 * 8 * 8,)))
            # copied from an MNIST recipe: expects 1568 inputs, the
            # conv stack above actually yields 2048
            .add(nn.Linear(7 * 7 * 32, 10).set_name("mnist_head"))
            .add(nn.LogSoftMax()))


def raw_error(model) -> str:
    """What you got WITHOUT the checker: run a batch, harvest the raw
    trace-time error (after real param init + device work; under jit
    this surfaces mid-compile with an XLA-internals stack)."""
    x = np.zeros((16, 3, 32, 32), np.float32)
    try:
        model.forward(x)
    except Exception as e:
        return f"{type(e).__name__}: {e}"
    raise AssertionError("the mis-wiring should have failed")


def preflight_error(model) -> str:
    """What you get WITH the checker: zero FLOPs, zero compiles, and the
    diagnostic names `sequential[7]/mnist_head` directly."""
    from bigdl_tpu.analysis import ShapeCheckError, spec
    try:
        model.check(spec(("b", 3, 32, 32)))
    except ShapeCheckError as e:
        return str(e)
    raise AssertionError("the mis-wiring should have failed")


def main(argv=None) -> dict:
    argparse.ArgumentParser(description=__doc__).parse_args(argv)
    model = build_miswired()

    pre = preflight_error(model)
    print("== pre-flight (Module.check, milliseconds, no compile) ==")
    print(pre)

    raw = raw_error(build_miswired())
    print()
    print("== the raw error it replaces (after init + device work) ==")
    print(raw)

    print()
    print("The pre-flight names the layer (`sequential[7]/mnist_head`) "
          "and runs under jax.eval_shape only; the raw path pays real "
          "initialization and fails inside the matmul with no layer "
          "attribution. Opt in before training or serving with "
          "Optimizer.set_preflight_spec(...) / "
          "ModelRegistry.load(..., input_spec=...).")
    return {"preflight": pre, "raw": raw}


if __name__ == "__main__":
    main()
