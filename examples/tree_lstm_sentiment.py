"""Tree-LSTM sentiment example (reference: example/treeLSTMSentiment —
constituency BinaryTreeLSTM over embedded tokens, classified at the root,
scored with TreeNNAccuracy).

Trees are full binary trees over token leaves; sentiment is the majority
polarity of the leaf tokens (synthetic stand-in for the SST data the
reference example downloads). The tree forward is vmapped over the batch
and the whole step is one jit.

    python examples/tree_lstm_sentiment.py --trees 200
"""
from __future__ import annotations

import argparse


def build_full_tree(n_leaves):
    """Children table of a full binary tree, nodes topologically ordered
    leaves-first, root LAST (BinaryTreeLSTM's contract); -1 = leaf."""
    import numpy as np
    children = [[-1, -1] for _ in range(n_leaves)]
    frontier = list(range(n_leaves))
    while len(frontier) > 1:
        nxt = []
        for i in range(0, len(frontier) - 1, 2):
            children.append([frontier[i], frontier[i + 1]])
            nxt.append(len(children) - 1)
        if len(frontier) % 2 == 1:
            nxt.append(frontier[-1])
        frontier = nxt
    return np.asarray(children, np.int32)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trees", type=int, default=200)
    ap.add_argument("--leaves", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=40)
    ap.add_argument("--hidden", type=int, default=24)
    ap.add_argument("--epochs", type=int, default=120)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import SGD, TreeNNAccuracy

    rng = np.random.RandomState(0)
    V, L, H = args.vocab, args.leaves, args.hidden
    children = build_full_tree(L)          # same topology for the batch
    n_nodes = len(children)

    # tokens 1..V/2 = negative polarity, V/2+1..V = positive; sentiment =
    # majority leaf polarity (labels 1/2, 1-based like the reference)
    tokens = rng.randint(1, V + 1, (args.trees, L)).astype(np.int32)
    labels = 1.0 + ((tokens > V // 2).mean(axis=1) > 0.5)

    tree = nn.BinaryTreeLSTM(H, H)
    embed = nn.LookupTable(V, H)
    head = nn.Linear(H, 2)
    for m in (tree, embed, head):
        m.ensure_initialized()
    params = {"tree": tree.get_parameters(),
              "embed": embed.get_parameters(),
              "head": head.get_parameters()}
    crit = nn.CrossEntropyCriterion()
    optim = SGD(learning_rate=args.lr, momentum=0.9)
    opt_state = optim.init_state(params)

    def tree_logits(p, toks):
        # leaves embed their token; internal nodes get zero input
        leaf_emb = embed.forward_fn(p["embed"], toks)
        emb = jnp.concatenate(
            [leaf_emb, jnp.zeros((n_nodes - L, H), leaf_emb.dtype)])
        hs = tree.forward_fn(p["tree"], [emb, children])
        return head.forward_fn(p["head"], hs[-1])  # root = last node

    def loss_fn(p, toks, y):
        logits = jax.vmap(lambda t: tree_logits(p, t))(toks)
        return crit.apply(logits, y), logits

    @jax.jit
    def step(p, o, toks, y, lr):
        (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            p, toks, y)
        p, o = optim.update(grads, o, p, lr)
        return p, o, loss

    toks_j = jnp.asarray(tokens)
    y_j = jnp.asarray(labels, jnp.float32)
    for epoch in range(args.epochs):
        lr = optim.update_hyper_parameter()
        params, opt_state, loss = step(params, opt_state, toks_j, y_j, lr)
    _, logits = loss_fn(params, toks_j, y_j)
    # TreeNNAccuracy scores the first/root output column
    acc, n = TreeNNAccuracy()(
        np.asarray(logits)[:, None, :],
        np.asarray(labels)[:, None]).result()
    print(f"final loss {float(loss):.4f} TreeNNAccuracy {acc:.3f} ({n})")
    return acc


if __name__ == "__main__":
    main()
