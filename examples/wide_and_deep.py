"""Wide-and-deep recommendation example over the sparse training feed
(reference: the SparseTensor input path — nn/SparseLinear.scala consumed
through dataset/MiniBatch.scala:587 SparseMiniBatch; the model shape
follows the classic wide-and-deep recommender).

The WIDE side is a huge one-hot/cross-feature vector that would be
wasteful dense: it stays COO end to end — ``SparseFeature`` per sample,
batched by ``SampleToMiniBatch`` into a static-shape padded COO, fed to
``SparseLinear`` as a device ``BCOO`` whose matmul lowers to
gather + MXU. The DEEP side is a small dense MLP; both heads sum into
class scores (CAddTable), the wide-and-deep fusion.

    python examples/wide_and_deep.py
"""
from __future__ import annotations

import argparse


def synthetic_interactions(n: int, wide_dim: int, deep_dim: int, seed=0):
    """Synthetic CTR-style data: label depends on a few wide crosses and
    a dense profile, so BOTH sides must learn. The GROUND-TRUTH weights
    come from a fixed seed — train and held-out splits share the same
    true model and differ only in their samples."""
    import numpy as np

    from bigdl_tpu.dataset import Sample, SparseFeature

    truth = np.random.RandomState(1234)
    w_wide = truth.randn(wide_dim) * (truth.rand(wide_dim) < 0.1)
    w_deep = truth.randn(deep_dim)
    rng = np.random.RandomState(seed)
    samples = []
    for _ in range(n):
        nnz = rng.randint(1, 6)
        hot = rng.choice(wide_dim, size=nnz, replace=False)
        deep = rng.randn(deep_dim).astype(np.float32)
        score = w_wide[hot].sum() + 0.5 * float(deep @ w_deep)
        label = 1.0 if score > 0 else 2.0
        wide = SparseFeature(hot[:, None], np.ones(nnz, np.float32),
                             (wide_dim,))
        samples.append(Sample([wide, deep], label))
    return samples


def build_model(wide_dim: int, deep_dim: int, n_classes: int = 2):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.sparse import SparseLinear

    wide = nn.Sequential().add(nn.SelectTable(1)) \
        .add(SparseLinear(wide_dim, n_classes))
    deep = (nn.Sequential().add(nn.SelectTable(2))
            .add(nn.Linear(deep_dim, 16)).add(nn.ReLU())
            .add(nn.Linear(16, n_classes)))
    return (nn.Sequential()
            .add(nn.ConcatTable().add(wide).add(deep))
            .add(nn.CAddTable())
            .add(nn.LogSoftMax()))


def main(argv=None):
    ap = argparse.ArgumentParser(description="wide-and-deep on sparse feed")
    ap.add_argument("-n", type=int, default=1024)
    ap.add_argument("--wideDim", type=int, default=200)
    ap.add_argument("--deepDim", type=int, default=8)
    ap.add_argument("-b", "--batchSize", type=int, default=32)
    ap.add_argument("-e", "--maxEpoch", type=int, default=3)
    args = ap.parse_args(argv)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import (DataSet, PaddingParam,
                                   SampleToMiniBatch)
    from bigdl_tpu.optim import (Evaluator, LocalOptimizer, SGD,
                                 Top1Accuracy, max_epoch)

    # fixed nnz: every batch shares one static shape, so the step
    # compiles exactly once (and multi-host feeds stay in sync)
    pad = PaddingParam(fixed_length=5)
    samples = synthetic_interactions(args.n, args.wideDim, args.deepDim)
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(args.batchSize, feature_padding=pad,
                          drop_remainder=True))
    model = build_model(args.wideDim, args.deepDim)
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                         batch_size=args.batchSize)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_epoch(args.maxEpoch))
    opt.optimize()
    print(f"final loss: {opt.driver_state['Loss']:.4f}")

    # held-out accuracy through the stock Evaluator — the sparse feed is
    # first-class there too
    val = synthetic_interactions(256, args.wideDim, args.deepDim, seed=1)
    val_ds = DataSet.array(val).transform(
        SampleToMiniBatch(args.batchSize, feature_padding=pad,
                          drop_remainder=True))
    results = Evaluator(model).test(val_ds, [Top1Accuracy()],
                                    batch_size=args.batchSize)
    acc, _ = results["Top1Accuracy"].result()
    print(f"held-out accuracy: {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
