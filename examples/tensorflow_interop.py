"""TensorFlow interop example — load a frozen GraphDef as a model, and
save a model back as a GraphDef TF can read (example/tensorflow/
{Load,Save}.scala + model.py: the reference froze a TF LeNet, loaded it
with Module.loadTF, and exported a BigDL model with saveTF).

    python examples/tensorflow_interop.py load  frozen_model.pb
    python examples/tensorflow_interop.py save  out_model.pb
    python examples/tensorflow_interop.py demo  # build+freeze with real
                                                # TF, round-trip, compare
"""
from __future__ import annotations

import argparse


def cmd_load(path: str):
    import numpy as np

    from bigdl_tpu.utils.tf_loader import load_tf_graph

    m = load_tf_graph(path).evaluate()
    print("inputs:", m.input_names)
    print("outputs:", m.output_names)
    x = np.random.RandomState(0).rand(1, 28, 28, 1).astype(np.float32)
    out = np.asarray(m.forward(x))
    print("forward ok, output shape", out.shape)
    return m


def cmd_save(path: str):
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils.tf_saver import save_tf_graph

    m = (nn.Sequential().add(nn.Reshape((784,)))
         .add(nn.Linear(784, 10)).add(nn.SoftMax()))
    m.ensure_initialized()
    names = save_tf_graph(path, m)
    print("wrote", path, names)
    return m


def cmd_demo():
    """Build a TF LeNet with REAL TensorFlow, freeze it in-process,
    import it, and check the two frameworks agree numerically."""
    import numpy as np
    import tensorflow as tf
    from tensorflow.python.framework import convert_to_constants

    from bigdl_tpu.utils.tf_loader import TFModule

    @tf.function
    def lenet(x):
        k1 = tf.constant(np.random.RandomState(0)
                         .randn(5, 5, 1, 6).astype(np.float32) * 0.1)
        k2 = tf.constant(np.random.RandomState(1)
                         .randn(400, 10).astype(np.float32) * 0.1)
        h = tf.nn.conv2d(x, k1, strides=1, padding="VALID")
        h = tf.nn.relu(h)
        h = tf.nn.max_pool2d(h, 2, 2, "VALID")
        h = tf.reshape(h, [1, -1])
        h = h[:, :400]
        return tf.matmul(h, k2)

    conc = lenet.get_concrete_function(
        tf.TensorSpec([1, 28, 28, 1], tf.float32))
    frozen = convert_to_constants.convert_variables_to_constants_v2(conc)
    graph_bytes = frozen.graph.as_graph_def().SerializeToString()

    x = np.random.RandomState(2).rand(1, 28, 28, 1).astype(np.float32)
    want = frozen(tf.constant(x))[0].numpy()
    m = TFModule(graph_bytes).evaluate()
    got = np.asarray(m.forward(x))
    err = float(np.abs(got - want).max())
    print(f"TF vs bigdl_tpu max err: {err:.2e}")
    assert err < 1e-4
    return err


def main(argv=None):
    ap = argparse.ArgumentParser(description="TF interop example")
    ap.add_argument("cmd", choices=["load", "save", "demo"])
    ap.add_argument("path", nargs="?", default="model.pb")
    args = ap.parse_args(argv)
    if args.cmd == "load":
        return cmd_load(args.path)
    if args.cmd == "save":
        return cmd_save(args.path)
    return cmd_demo()


if __name__ == "__main__":
    main()
