"""Telemetry tour (docs/telemetry.md): enable the span tracer, train a
small model through the real Optimizer loop while serving concurrent
traffic, and export the SAME run four ways — a Chrome trace JSON
(Perfetto / chrome://tracing), TensorBoard scalars, a Prometheus text
file, and a JSONL snapshot — then print the where-did-the-time-go
attribution the `tools.diagnose` CLI renders.

    python examples/telemetry_tour.py --steps 8 --out-dir /tmp/telemetry
"""
from __future__ import annotations

import argparse
import os


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=8,
                    help="optimizer iterations to run")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--out-dir", default="/tmp/bigdl_telemetry_tour",
                    help="where the four exports land")
    args = ap.parse_args(argv)

    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu import telemetry
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import SGD, LocalOptimizer, max_iteration
    from bigdl_tpu.serving import InferenceService, ServingConfig
    from bigdl_tpu.tools.diagnose import aggregate_spans, attribution
    from bigdl_tpu.utils.random import RandomGenerator

    RandomGenerator.set_seed(42)
    os.makedirs(args.out_dir, exist_ok=True)

    # 1. turn the span tracer on (off by default: span() is then a
    # single flag check returning a shared no-op context manager)
    telemetry.enable()

    # 2. a training run — the Optimizer's host loop records its
    # data-wait/compute phases as spans AND into the train/optimizer/*
    # histograms of the default registry, so the trace and
    # Metrics.summary() carry the same numbers
    rng = np.random.RandomState(0)
    din, classes = 32, 4
    x = rng.randn(256, din).astype(np.float32)
    y = (np.arange(256) % classes + 1).astype(np.float32)
    ds = DataSet.array([Sample(x[i], y[i]) for i in range(len(x))]) \
        .transform(SampleToMiniBatch(args.batch_size))
    model = (nn.Sequential().add(nn.Linear(din, 32)).add(nn.Tanh())
             .add(nn.Linear(32, classes)).add(nn.LogSoftMax()))
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                         batch_size=args.batch_size)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(args.steps))

    # 3. concurrent serving traffic reporting into the SAME registry
    # (pass telemetry.registry(); the default is a private one so
    # independent services never mix counts)
    svc = InferenceService(
        config=ServingConfig(max_batch_size=8, buckets=(8,)),
        metrics_registry=telemetry.registry())
    serve_model = nn.Sequential().add(nn.Linear(din, classes))
    serve_model.ensure_initialized()
    svc.load("tour", serve_model, warmup_shape=(din,))
    import threading
    stop = threading.Event()

    def burst():
        while not stop.is_set():
            try:
                svc.predict_batch("tour", x[:4], timeout_ms=500)
            except Exception:
                # deadline misses under compile pressure / shutdown
                # drain are expected traffic outcomes; keep bursting
                pass

    t = threading.Thread(target=burst, name="tour-burst", daemon=True)
    t.start()
    try:
        opt.optimize()
    finally:
        stop.set()
        t.join(timeout=5)
        svc.shutdown(drain=True)

    # 4. export the run four ways
    trace_path = os.path.join(args.out_dir, "trace.json")
    n_spans = telemetry.export_chrome_trace(trace_path)
    print(f"chrome trace: {trace_path} ({n_spans} spans) — load it in "
          "Perfetto or chrome://tracing")

    reg = telemetry.registry()
    tb = telemetry.TensorBoardExporter(reg, os.path.join(args.out_dir,
                                                         "tb"))
    n_scalars = tb.export(step=args.steps)
    tb.close()
    print(f"tensorboard: {tb.log_dir} ({n_scalars} scalars)")

    prom_path = os.path.join(args.out_dir, "metrics.prom")
    telemetry.write_prometheus(reg, prom_path)
    print(f"prometheus text: {prom_path}")

    jsonl_path = os.path.join(args.out_dir, "metrics.jsonl")
    telemetry.snapshot_to_jsonl(jsonl_path, step=args.steps,
                                meta={"tool": "telemetry_tour"})
    print(f"jsonl snapshot: {jsonl_path}")

    # 5. the diagnose attribution, inline (same code path as
    # `python -m bigdl_tpu.tools.diagnose`)
    rows = attribution(aggregate_spans(
        telemetry.tracer().chrome_trace_events()))
    print("where did the time go:")
    for r in rows:
        print(f"  {r['group']:>7s}  {r['name']:<34s} "
              f"{r['total_s']:8.4f} s ({100 * r['share']:5.1f}%)")
    print(f"optimizer view: {opt.metrics.summary()}")
    return {"trace": trace_path, "prometheus": prom_path,
            "jsonl": jsonl_path, "tensorboard": tb.log_dir,
            "spans": rows, "optimizer": opt.metrics.summary()}


if __name__ == "__main__":
    main()
