"""Online generation example (docs/serving.md "Generation"): register a
TransformerLM in a GenerationService, stream greedy and sampled
generations through the bucketed KV-cache decode engine with continuous
batching, hot-swap a new version under live decode traffic, and print
the generation metrics (tokens/sec ingredients, TTFT, occupancy).

    python examples/online_generation.py --requests 8
"""
from __future__ import annotations

import argparse


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=8,
                    help="concurrent generation requests to stream")
    ap.add_argument("--max-new", type=int, default=12,
                    help="tokens to generate per request")
    ap.add_argument("--slots", type=int, default=4,
                    help="KV-cache slots (continuous-batching width)")
    ap.add_argument("--max-len", type=int, default=64,
                    help="cache time axis: prompt + generation bound")
    ap.add_argument("--buckets", default=None,
                    help="comma-separated length-bucket ladder (top "
                         "rung must equal --max-len); default powers "
                         "of two — fewer rungs, fewer compiles, more "
                         "padded attention")
    args = ap.parse_args(argv)

    import numpy as np

    from bigdl_tpu.generation import GenerationConfig, GenerationService
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.utils.random import RandomGenerator

    RandomGenerator.set_seed(42)
    vocab = 64
    model = TransformerLM(vocab_size=vocab, hidden_size=32,
                          num_layers=2, num_heads=4,
                          max_len=args.max_len).evaluate()
    model.ensure_initialized()

    buckets = (tuple(int(b) for b in args.buckets.split(","))
               if args.buckets else None)
    svc = GenerationService(config=GenerationConfig(
        slots=args.slots, max_len=args.max_len, prefill_rows=2,
        length_buckets=buckets))
    # load() warms the prefill+decode program PAIR for every length
    # bucket before the version takes traffic: K rungs => <= 2K
    # compiles, and no live request ever eats one
    svc.load("lm", model)
    print(f"loaded lm v1, ladder={list(svc.ladder)}, "
          f"warm compiles={svc.compile_count('lm')} "
          f"(bound: {2 * len(svc.ladder)})")

    # a burst of ragged prompts: more requests than slots, so the loop
    # admits into freed slots step by step — continuous batching
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, vocab, rng.randint(3, 10))
               for _ in range(args.requests)]
    streams = [svc.generate("lm", p, max_new_tokens=args.max_new)
               for p in prompts]
    print(f"submitted {len(streams)} requests into {args.slots} slots")

    # stream the first request token by token (greedy = deterministic)
    first = [tok for tok in streams[0]]
    print(f"request 0 streamed: {first} ({streams[0].finish_reason})")
    outs = [s.result(timeout=120) for s in streams]
    assert all(len(o) == args.max_new for o in outs)

    # seeded sampling: same seed => identical stream, new seed differs
    a = svc.generate("lm", prompts[0], max_new_tokens=args.max_new,
                     temperature=0.8, top_k=8, seed=7).result(timeout=120)
    b = svc.generate("lm", prompts[0], max_new_tokens=args.max_new,
                     temperature=0.8, top_k=8, seed=7).result(timeout=120)
    assert np.array_equal(a, b), "seeded sampling must be deterministic"
    print(f"sampled (T=0.8, top_k=8, seed=7): {[int(t) for t in a]}")

    # hot-swap v2 under live decode: in-flight generations finish on
    # v1, new admissions decode v2
    live = svc.generate("lm", prompts[0],
                        max_new_tokens=args.max_new)
    RandomGenerator.set_seed(7)
    model2 = TransformerLM(vocab_size=vocab, hidden_size=32,
                           num_layers=2, num_heads=4,
                           max_len=args.max_len).evaluate()
    model2.ensure_initialized()
    svc.load("lm", model2)
    v1_out = live.result(timeout=120)
    v2_out = svc.generate("lm", prompts[0],
                          max_new_tokens=args.max_new).result(timeout=120)
    assert np.array_equal(v1_out, outs[0]), \
        "in-flight generation must finish on the version it started on"
    print(f"hot-swapped to v2 mid-decode: v1 stream unchanged, "
          f"v2 answers {[int(t) for t in v2_out]}")

    metrics = svc.metrics("lm")
    for k in sorted(metrics):
        print(f"  {k:>22}: {metrics[k]:.3f}")
    svc.shutdown()
    return metrics


if __name__ == "__main__":
    main()
