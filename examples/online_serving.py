"""Online inference example (docs/serving.md): train a tiny MNIST-style
MLP, register it in an InferenceService, and serve randomized
single-sample traffic through the dynamic micro-batcher — then hot-swap
an int8-quantized version of the same model behind the same name, with
zero downtime, and print the serving metrics the service exports to
TensorBoard.

    python examples/online_serving.py --requests 64
"""
from __future__ import annotations

import argparse


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=64,
                    help="randomized single-sample requests to serve")
    ap.add_argument("--batch-size", type=int, default=16,
                    help="max micro-batch size (bucket ladder top rung)")
    ap.add_argument("--wait-ms", type=float, default=2.0,
                    help="max time an underfilled batch waits to fill")
    ap.add_argument("--log-dir", default=None,
                    help="TensorBoard dir for the serving scalars")
    args = ap.parse_args(argv)

    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.serving import InferenceService, ServingConfig
    from bigdl_tpu.utils.random import RandomGenerator

    RandomGenerator.set_seed(42)
    din, dout = 28 * 28, 10
    model = (nn.Sequential().add(nn.Linear(din, 64)).add(nn.Tanh())
             .add(nn.Linear(64, dout)).add(nn.LogSoftMax()))

    svc = InferenceService(config=ServingConfig(
        max_batch_size=args.batch_size, max_wait_ms=args.wait_ms))
    # warmup_shape pre-compiles every bucket: the first real request
    # never pays an XLA compile
    svc.load("mnist", model, warmup_shape=(din,))
    print(f"loaded mnist v1, ladder={list(svc.ladder)}, "
          f"warm compiles={svc.compile_count('mnist')}")

    rng = np.random.RandomState(0)
    xs = rng.randn(args.requests, din).astype(np.float32)
    futs = [svc.predict_async("mnist", xs[i])
            for i in range(args.requests)]
    outs = np.stack([f.result(timeout=60) for f in futs])
    ref = np.asarray(model.forward(xs))
    assert np.allclose(outs, ref, atol=1e-5)

    # hot-swap an int8-quantized v2 behind the same name: in-flight
    # requests finish on v1, every later batch serves v2
    svc.load("mnist", model, quantize=True, warmup_shape=(din,))
    agree = float(np.mean(
        [svc.predict("mnist", xs[i]).argmax() == ref[i].argmax()
         for i in range(min(args.requests, 16))]))
    print(f"hot-swapped to int8 v2; top-1 agreement vs float: {agree:.2f}")

    metrics = svc.metrics("mnist")
    for k in sorted(metrics):
        print(f"  {k:>20}: {metrics[k]:.3f}")
    if args.log_dir:
        from bigdl_tpu.visualization import ServingSummary
        summary = ServingSummary(args.log_dir, "serving_example")
        svc.export_metrics(summary, step=1)
        summary.close()
        print(f"serving scalars written under {args.log_dir} "
              "(tensorboard --logdir there)")
    svc.shutdown()
    return metrics


if __name__ == "__main__":
    main()
