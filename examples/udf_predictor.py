"""UDF predictor example (reference: example/udfpredictor —
DataframePredictor.scala:25 serves a trained text classifier as a SQL
UDF). Without Spark SQL, the analogue is a plain predict function
applied over a column of raw strings — usable from any dataframe
library (pandas .apply, etc.).

    python examples/udf_predictor.py --demo
"""
from __future__ import annotations

import argparse
from typing import Callable, List, Sequence


def make_text_udf(model, dictionary, seq_len: int) -> Callable:
    """Returns predict(texts) -> 1-based class labels; the UDF closure
    captures the trained model + vocabulary like the reference's
    broadcast model."""
    import numpy as np

    from bigdl_tpu.dataset import tokenize
    from examples.text_classification import encode_text_ids

    model.evaluate()  # serving: dropout etc. must be inert

    def predict(texts: Sequence[str]) -> List[int]:
        X = np.stack([encode_text_ids(tokenize(t), dictionary, seq_len)
                      for t in texts])
        out = np.asarray(model.forward(X))
        return (out.argmax(-1) + 1).tolist()

    return predict


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--demo", action="store_true")
    args = ap.parse_args(argv)
    if not args.demo:
        ap.print_help()
        return

    # train a tiny classifier on the synthetic corpus, then serve it
    from examples.text_classification import (build_model, encode_text_ids,
                                              synthetic_corpus)
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import (DataSet, Dictionary, Sample,
                                   SampleToMiniBatch, tokenize)
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_epoch

    rng = np.random.RandomState(0)
    texts, labels = synthetic_corpus(200, 2, rng)
    token_lists = [tokenize(t) for t in texts]
    d = Dictionary(token_lists, vocab_size=200)
    seq_len = 40

    X = np.stack([encode_text_ids(t, d, seq_len) for t in token_lists])
    y = np.asarray(labels, np.float32)
    ds = DataSet.array([Sample(x, t) for x, t in zip(X, y)]) \
        .transform(SampleToMiniBatch(32))
    model = build_model(d.vocab_size() + 1, 16, 2)
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=32)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_epoch(6))
    opt.optimize()

    udf = make_text_udf(model, d, seq_len)
    demo_texts, demo_labels = synthetic_corpus(8, 2, np.random.RandomState(7))
    preds = udf(demo_texts)
    hits = sum(int(p == int(l)) for p, l in zip(preds, demo_labels))
    print(f"udf predictions: {preds} (labels {[int(l) for l in demo_labels]}"
          f", {hits}/8 correct)")
    return preds


if __name__ == "__main__":
    main()
