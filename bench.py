"""Benchmark harness (reference: models/utils/DistriOptimizerPerf.scala:38 —
synthetic-data throughput for the zoo models).

Runs ResNet-50 ImageNet *training* steps (fwd+bwd+SGD update, the BASELINE
north-star config) on the available accelerator with synthetic data and
prints ONE JSON line:

    {"metric": ..., "value": imgs/sec, "unit": "images/sec", "vs_baseline": r}

Each timed call scans BENCH_SCAN full training steps on-device (params,
optimizer state and BN statistics threaded step to step, a fresh random
batch generated per step) so the measurement is pure device throughput, not
per-dispatch host round-trips. Set BENCH_SCAN=1 for the old
one-step-per-dispatch behavior.

Baseline: the reference publishes no absolute numbers (BASELINE.md); the
working Xeon baseline recorded there is 56 img/s/node (BigDL-paper-era
dual-socket Xeon ResNet-50 estimate) until a measured value replaces it.
"""
import json
import os
import time

# BASELINE.md "working baseline" — see §North star.
REFERENCE_BASELINE_IMGS_PER_SEC = 56.0


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import ResNet
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random import RandomGenerator

    batch = int(os.environ.get("BENCH_BATCH", 256))
    iters = int(os.environ.get("BENCH_ITERS", 6))
    warmup = int(os.environ.get("BENCH_WARMUP", 1))
    scan = int(os.environ.get("BENCH_SCAN", 8))

    platform = jax.devices()[0].platform
    # bf16 compute on accelerators (TPU-native analogue of the reference's
    # fp16 gradient compression); f32 master params.
    if platform != "cpu":
        Engine.set_compute_dtype(jnp.bfloat16)

    RandomGenerator.set_seed(1)
    model = ResNet(1000, depth=50, dataset="ImageNet").training()
    model.ensure_initialized()
    criterion = nn.CrossEntropyCriterion()
    optim = SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4,
                nesterov=True, dampening=0.0)

    params = model.get_parameters()
    mstate = model.get_state()
    opt_state = optim.init_state(params)
    step = build_train_step(model, criterion, optim)

    def scan_body(carry, key):
        params, opt_state, mstate = carry
        kx, ky, kr = jax.random.split(key, 3)
        x = jax.random.uniform(kx, (batch, 3, 224, 224), jnp.float32)
        y = jax.random.randint(ky, (batch,), 1, 1001).astype(jnp.float32)
        params, opt_state, mstate, loss = step(params, opt_state, mstate,
                                               kr, 0.1, x, y)
        return (params, opt_state, mstate), loss

    @jax.jit
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    root = jax.random.PRNGKey(0)
    carry = (params, opt_state, mstate)
    for i in range(warmup):
        keys = jax.random.split(jax.random.fold_in(root, i), scan)
        carry, losses = run_chunk(carry, keys)
    if warmup:
        float(losses.sum())  # sync: losses depend on every prior params

    t0 = time.time()
    for i in range(iters):
        keys = jax.random.split(jax.random.fold_in(root, 1000 + i), scan)
        carry, losses = run_chunk(carry, keys)
    float(losses.sum())  # data dependency forces completion of the chain
    dt = time.time() - t0

    imgs_per_sec = batch * scan * iters / dt
    result = {
        "metric": "resnet50_imagenet_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(imgs_per_sec / REFERENCE_BASELINE_IMGS_PER_SEC,
                             3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
