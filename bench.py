"""Benchmark harness (reference: models/utils/DistriOptimizerPerf.scala:38 —
synthetic-data throughput for the zoo models).

Runs ResNet-50 ImageNet *training* steps (fwd+bwd+SGD update, the BASELINE
north-star config) on the available accelerator with synthetic data and
prints ONE JSON line:

    {"metric": ..., "value": imgs/sec, "unit": "images/sec", "vs_baseline": r}

Each timed call scans BENCH_SCAN full training steps on-device (params,
optimizer state and BN statistics threaded step to step, a fresh random
batch generated per step) so the measurement is pure device throughput, not
per-dispatch host round-trips. Set BENCH_SCAN=1 for the old
one-step-per-dispatch behavior.

Baseline: the reference publishes no absolute numbers (BASELINE.md); the
working Xeon baseline recorded there is 56 img/s/node (BigDL-paper-era
dual-socket Xeon ResNet-50 estimate) until a measured value replaces it.
"""
import functools
import json
import os
import time

# BASELINE.md "working baseline" — see §North star.
REFERENCE_BASELINE_IMGS_PER_SEC = 56.0

# The JSON line's schema version, checked by the regression sentinel
# (python -m bigdl_tpu.tools.regress): bump it whenever a tracked key
# is RENAMED or changes meaning (adding keys is compatible — the
# sentinel reports unknown-to-it keys as "new" and ignores config
# echo). Version 2 = the documented stable key set: "metric"/"value"/
# "unit"/"vs_baseline" plus the optional per-row keys (steps_per_sync,
# *_per_sec*, *_ms_p*, PROGRAMS' programs_*_mfu/_hbm_bytes, ...).
BENCH_SCHEMA_VERSION = 2


def _maybe_metrics_snapshot(result):
    """One flag, default off (BIGDL_METRICS_JSONL=path): append a
    telemetry snapshot — any phase instruments the run populated plus
    this result as meta — so BENCH trajectories carry breakdowns, not
    just the headline number."""
    jsonl = os.environ.get("BIGDL_METRICS_JSONL")
    if jsonl:
        import bigdl_tpu.telemetry as telemetry
        telemetry.snapshot_to_jsonl(jsonl, meta=dict(result, tool="bench"))


def _build_decoded_pool(default_n: int = 256):
    """Synthesize ImageNet-shaped JPEGs (375x500 q90), decode + scale
    shorter side to 256 + center-crop — the decode-once cost real
    training pays on its first epoch. Returns (pool u8 [N,3,256,256],
    labels, decode_imgs_per_sec)."""
    import io

    import numpy as np
    from PIL import Image

    from bigdl_tpu.dataset.imagenet import decode_image

    pool_n = int(os.environ.get("BENCH_FED_POOL", default_n))
    rng = np.random.RandomState(0)
    t0 = time.time()
    pool = np.empty((pool_n, 3, 256, 256), np.uint8)
    for i in range(pool_n):
        arr = rng.randint(0, 255, (375, 500, 3), np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=90)
        img = decode_image(buf.getvalue(), scale=256)
        h, w = img.shape[:2]
        oy, ox = (h - 256) // 2, (w - 256) // 2
        pool[i] = img[oy:oy + 256, ox:ox + 256].transpose(2, 0, 1)
    decode_rate = pool_n / (time.time() - t0)
    labels = rng.randint(1, 1001, pool_n).astype(np.float32)
    return pool, labels, decode_rate


def _fed_minibatch_chunks(batch, scan):
    """Real-input feed: decode JPEGs once into a RAM cache (the reference
    caches *decoded* ImageNet in BlockManager memory across epochs —
    DataSet.scala CachedDistriDataSet:240), then augment per step with the
    native C++ loader (random crop+flip+normalize) and stage stacked
    scan-chunks to device while the previous chunk computes.

    Yields MiniBatch(xs[scan,B,3,224,224] uint8, ys[scan,B]) already on
    device; normalization runs on device where it fuses into the first
    conv (uint8 crosses the host->device link at 1/4 the float32 bytes —
    the link, ~0.45 GB/s through the tunnel, is the feed bottleneck).
    """
    from bigdl_tpu.dataset import native_available
    from bigdl_tpu.dataset.sample import MiniBatch

    if not native_available():
        raise RuntimeError("fed bench needs the native loader")
    from bigdl_tpu.native import NativeBatchLoaderU8

    pool, labels, decode_rate = _build_decoded_pool()

    loader = NativeBatchLoaderU8(
        pool, labels, batch, crop=(224, 224), pad=0, flip=True,
        num_threads=int(os.environ.get("BENCH_FED_THREADS",
                                       os.cpu_count() or 2)),
        prefetch=4)

    # Strictly serial, PIECEWISE staging. Two tunnel pathologies shape
    # this loop (measured):
    #  - transfers issued while a step executes stall both by ~10-60x, so
    #    transfer and compute must alternate on one thread (on real
    #    hosts, overlap with dataset.prefetch.device_prefetch instead);
    #  - one big device_put falls off a cliff above a few hundred MB
    #    (1.23GB stacked chunk: 14-37s; the same bytes as 8 x 38MB
    #    batches: ~0.1s each, up to ~1.1GB/s) — so each batch is
    #    transferred separately and the scan chunk is stacked ON DEVICE.
    import jax

    def chunks():
        while True:
            bs = [loader.next_batch() for _ in range(scan)]
            xs = [jax.device_put(b[0]) for b in bs]
            ys = [jax.device_put(b[1]) for b in bs]
            for a in xs:
                a.block_until_ready()
            for a in ys:
                a.block_until_ready()
            yield MiniBatch(xs, ys)

    return chunks(), loader, decode_rate


def _row_enabled(flag_name: str, platform: str) -> bool:
    """One gate for every optional bench row: the env flag "0" disables
    it everywhere, "1" forces it on, and otherwise it runs only off-CPU
    (on CPU smoke runs the extra compiles would dominate CI)."""
    flag = os.environ.get(flag_name, "")
    return flag != "0" and (platform != "cpu" or flag == "1")


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import ResNet
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random import RandomGenerator

    batch = int(os.environ.get("BENCH_BATCH", 256))
    iters = int(os.environ.get("BENCH_ITERS", 6))
    warmup = int(os.environ.get("BENCH_WARMUP", 1))
    scan = int(os.environ.get("BENCH_SCAN", 8))

    platform = jax.devices()[0].platform
    # bf16 compute on accelerators (TPU-native analogue of the reference's
    # fp16 gradient compression); f32 master params.
    if platform != "cpu":
        Engine.set_compute_dtype(jnp.bfloat16)

    RandomGenerator.set_seed(1)
    model = ResNet(1000, depth=50, dataset="ImageNet").training()
    model.ensure_initialized()
    criterion = nn.CrossEntropyCriterion()
    optim = SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4,
                nesterov=True, dampening=0.0)

    params = model.get_parameters()
    mstate = model.get_state()
    opt_state = optim.init_state(params)
    step = build_train_step(model, criterion, optim)

    mode = os.environ.get("BENCH_MODE", "synthetic")

    if mode == "cached":
        # Device-cached real-input variant: decoded images resident in
        # HBM as uint8, augmentation (random crop+flip+normalize) fused
        # into the jitted step — zero per-step host->device traffic (the
        # TPU-native form of the reference's decoded-image executor cache,
        # DataSet.scala CachedDistriDataSet:240).
        from bigdl_tpu.dataset.device_dataset import DeviceCachedArrayDataSet
        from bigdl_tpu.dataset.imagenet import IMAGENET_MEAN, IMAGENET_STD

        pool, labels, decode_rate = _build_decoded_pool()
        ds = DeviceCachedArrayDataSet(
            pool, labels, batch, crop=(224, 224), flip=True,
            mean=IMAGENET_MEAN, std=IMAGENET_STD)

        def scan_body_cached(carry, key_it):
            params, opt_state, mstate, ep, pos = carry
            kb, kr = jax.random.split(key_it)
            # epoch-exact permutation walk; the (epoch, pos) cursor stays
            # < 2n so it never overflows int32 however long the run
            x, y = ds.batch_fn(kb, epoch=ep, pos=pos)
            params, opt_state, mstate, loss = step(
                params, opt_state, mstate, kr, 0.1, x, y)
            pos = pos + batch
            ep = ep + pos // ds.n
            pos = pos % ds.n
            return (params, opt_state, mstate, ep, pos), loss

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_chunk_cached(carry, keys):
            return lax.scan(scan_body_cached, carry, keys)

        root = jax.random.PRNGKey(0)
        carry = (params, opt_state, mstate, jnp.int32(0), jnp.int32(0))
        for i in range(warmup):
            keys = jax.random.split(jax.random.fold_in(root, i), scan)
            carry, losses = run_chunk_cached(carry, keys)
        if warmup:
            float(losses.sum())
        t0 = time.time()
        for i in range(iters):
            keys = jax.random.split(jax.random.fold_in(root, 1000 + i),
                                    scan)
            carry, losses = run_chunk_cached(carry, keys)
        float(losses.sum())
        dt = time.time() - t0
        imgs_per_sec = batch * scan * iters / dt
        result = {
            "schema_version": BENCH_SCHEMA_VERSION,
            "metric":
                "resnet50_imagenet_train_devcached_imgs_per_sec_per_chip",
            "value": round(imgs_per_sec, 2),
            "unit": "images/sec",
            "vs_baseline": round(
                imgs_per_sec / REFERENCE_BASELINE_IMGS_PER_SEC, 3),
            "first_epoch_decode_imgs_per_sec_per_core":
                round(decode_rate, 1),
        }
        print(json.dumps(result))
        _maybe_metrics_snapshot(result)
        return

    if mode == "rotate":
        # Shard-rotation variant: the decoded pool is >2x an artificial
        # HBM budget of two shard slots; training runs on the resident
        # shard while the next one streams host->device in cliff-safe
        # pieces between scan-chunks (the composition that makes real
        # ImageNet — ~250 GB decoded vs 128 GB pod HBM — train at
        # near-cached rates; DataSet.scala:470-552's cluster-rate IO).
        from bigdl_tpu.dataset.device_dataset import ShardRotator
        from bigdl_tpu.dataset.imagenet import IMAGENET_MEAN, IMAGENET_STD

        pool, labels, decode_rate = _build_decoded_pool(1024)
        n_shards = int(os.environ.get("BENCH_ROTATE_SHARDS", 4))
        shard = len(pool) // n_shards

        def provider(i):
            return (pool[i * shard:(i + 1) * shard],
                    labels[i * shard:(i + 1) * shard])

        rot = ShardRotator(provider, n_shards, batch, crop=(224, 224),
                           flip=True, mean=IMAGENET_MEAN,
                           std=IMAGENET_STD)
        tmpl = rot.template

        def scan_body_rot(carry, key_it, images, lbls):
            params, opt_state, mstate, ep, pos = carry
            kb, kr = jax.random.split(key_it)
            x, y = tmpl.batch_fn_on(images, lbls, kb, epoch=ep, pos=pos)
            params, opt_state, mstate, loss = step(
                params, opt_state, mstate, kr, 0.1, x, y)
            pos = pos + batch
            ep = ep + pos // tmpl.n
            pos = pos % tmpl.n
            return (params, opt_state, mstate, ep, pos), loss

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_chunk_rot(carry, keys, images, lbls):
            return lax.scan(
                lambda c, k: scan_body_rot(c, k, images, lbls),
                carry, keys)

        # chunks per shard ~= one shard-epoch (>=1)
        per_shard = max(1, shard // (batch * scan))
        root = jax.random.PRNGKey(0)
        carry = (params, opt_state, mstate, jnp.int32(0), jnp.int32(0))
        for i in range(max(warmup, 1)):
            keys = jax.random.split(jax.random.fold_in(root, i), scan)
            carry, losses = run_chunk_rot(carry, keys, rot.images,
                                          rot.labels)
        float(losses.sum())
        t0 = time.time()
        t_end = t0
        done = 0
        i = 0
        while done < iters * scan:
            for _ in range(per_shard):
                keys = jax.random.split(
                    jax.random.fold_in(root, 1000 + i), scan)
                carry, losses = run_chunk_rot(carry, keys, rot.images,
                                              rot.labels)
                float(losses.sum())   # complete compute, THEN transfer
                t_end = time.time()   # clock stops at counted work only
                rot.pump()            # (alternation rule on the tunnel)
                done += scan
                i += 1
                if done >= iters * scan:
                    break
            if done >= iters * scan:
                break  # don't time staging a shard that never trains
            while not rot.staged:
                rot.pump()
            rot.rotate()
        dt = t_end - t0
        imgs_per_sec = batch * done / dt
        result = {
            "schema_version": BENCH_SCHEMA_VERSION,
            "metric":
                "resnet50_imagenet_train_shardrotate_imgs_per_sec_per_chip",
            "value": round(imgs_per_sec, 2),
            "unit": "images/sec",
            "vs_baseline": round(
                imgs_per_sec / REFERENCE_BASELINE_IMGS_PER_SEC, 3),
            "pool_images": len(pool),
            "hbm_budget_images": 2 * shard,
            "chunk_bytes": rot.chunk_bytes,
            "first_epoch_decode_imgs_per_sec_per_core":
                round(decode_rate, 1),
        }
        print(json.dumps(result))
        _maybe_metrics_snapshot(result)
        return

    if mode == "fed":
        # Real-input variant: host-augmented batches (decoded-image RAM
        # cache + native C++ crop/flip/normalize) staged to device.
        from bigdl_tpu.dataset.imagenet import IMAGENET_MEAN, IMAGENET_STD
        mean = jnp.asarray(IMAGENET_MEAN, jnp.float32).reshape(1, 3, 1, 1)
        std = jnp.asarray(IMAGENET_STD, jnp.float32).reshape(1, 3, 1, 1)

        def scan_body_fed(carry, xy):
            params, opt_state, mstate = carry
            x, y = xy
            # on-device normalize: uint8 -> f32, fused into the first conv
            x = (x.astype(jnp.float32) - mean) / std
            kr = jax.random.PRNGKey(0)
            params, opt_state, mstate, loss = step(
                params, opt_state, mstate, kr, 0.1, x, y)
            return (params, opt_state, mstate), loss

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_chunk_fed(carry, xs, ys):
            # xs/ys arrive as lists of per-batch device arrays (see
            # _fed_minibatch_chunks) — stack on device, then scan
            return lax.scan(scan_body_fed, carry,
                            (jnp.stack(xs), jnp.stack(ys)))

        chunks, loader, decode_rate = _fed_minibatch_chunks(batch, scan)
        try:
            carry = (params, opt_state, mstate)
            for _ in range(warmup):
                b = next(chunks)
                carry, losses = run_chunk_fed(carry, b.input, b.target)
            if warmup:
                float(losses.sum())
            t0 = time.time()
            for _ in range(iters):
                b = next(chunks)
                carry, losses = run_chunk_fed(carry, b.input, b.target)
            float(losses.sum())
            dt = time.time() - t0
        finally:
            loader.close()
        imgs_per_sec = batch * scan * iters / dt
        result = {
            "schema_version": BENCH_SCHEMA_VERSION,
            "metric": "resnet50_imagenet_train_fed_imgs_per_sec_per_chip",
            "value": round(imgs_per_sec, 2),
            "unit": "images/sec",
            "vs_baseline": round(
                imgs_per_sec / REFERENCE_BASELINE_IMGS_PER_SEC, 3),
            "first_epoch_decode_imgs_per_sec_per_core":
                round(decode_rate, 1),
        }
        print(json.dumps(result))
        _maybe_metrics_snapshot(result)
        return

    def scan_body(carry, key):
        params, opt_state, mstate = carry
        kx, ky, kr = jax.random.split(key, 3)
        x = jax.random.uniform(kx, (batch, 3, 224, 224), jnp.float32)
        y = jax.random.randint(ky, (batch,), 1, 1001).astype(jnp.float32)
        params, opt_state, mstate, loss = step(params, opt_state, mstate,
                                               kr, 0.1, x, y)
        return (params, opt_state, mstate), loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    root = jax.random.PRNGKey(0)
    carry = (params, opt_state, mstate)
    for i in range(warmup):
        keys = jax.random.split(jax.random.fold_in(root, i), scan)
        carry, losses = run_chunk(carry, keys)
    if warmup:
        float(losses.sum())  # sync: losses depend on every prior params

    t0 = time.time()
    for i in range(iters):
        keys = jax.random.split(jax.random.fold_in(root, 1000 + i), scan)
        carry, losses = run_chunk(carry, keys)
    float(losses.sum())  # data dependency forces completion of the chain
    dt = time.time() - t0

    imgs_per_sec = batch * scan * iters / dt
    result = {
        "schema_version": BENCH_SCHEMA_VERSION,
        "metric": "resnet50_imagenet_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(imgs_per_sec / REFERENCE_BASELINE_IMGS_PER_SEC,
                             3),
        "steps_per_sync": scan,
    }
    # steps/sec at K=1 vs K=8 fused windows: quantifies what bounded
    # async dispatch buys over per-step host sync (the Optimizer's
    # set_steps_per_sync knob). Skipped on CPU smoke runs unless forced
    # — two extra compiles would dominate CI.
    if _row_enabled("BENCH_SYNC_COMPARE", platform):
        from bigdl_tpu.tools.sync_compare import measure_sync_compare

        def build(k):
            if k == scan:
                return run_chunk  # identical program: reuse, no recompile

            @functools.partial(jax.jit, donate_argnums=(0,))
            def chunk_k(c, keys):
                return lax.scan(scan_body, c, keys)
            return chunk_k

        rates, carry = measure_sync_compare(
            build, carry,
            lambda k, i: jax.random.split(
                jax.random.fold_in(root, 7000 + 100 * k + i + 1), k),
            total=max(8, int(os.environ.get("BENCH_SYNC_STEPS", 16))))
        result.update({name: round(r, 3) for name, r in rates.items()})
    # second tracked metric: TransformerLM training tokens/s (the
    # net-new flagship family; a regression here must be visible to the
    # driver's scoreboard, not just ResNet-50). Skipped on CPU smoke
    # runs unless forced — the compile alone would dominate CI.
    if _row_enabled("BENCH_LM", platform):
        result["transformerlm_tokens_per_sec_per_chip"] = round(
            _bench_transformer_lm(), 1)
    # third tracked scalar: forward-only (serving) throughput — the
    # reference's Predictor half of the product (Predictor.scala:35);
    # the full bf16-vs-int8 inference table lives in BASELINE.md
    if _row_enabled("BENCH_INFER", platform):
        # the original params buffers were DONATED to the train chunk;
        # the live values ride the final carry
        result["resnet50_inference_imgs_per_sec_per_chip"] = round(
            _bench_inference(model, carry[0], carry[2], batch), 1)
    # fourth tracked row: GENERATION — TransformerLM autoregressive
    # serving through the KV-cache decode engine (tokens/sec plus
    # TTFT / per-token latency percentiles from the service's own
    # histograms). Skipped on CPU smoke runs unless forced — the 2K
    # program warmup would dominate CI.
    if _row_enabled("BENCH_GEN", platform):
        result.update(_bench_generation())
    # fifth tracked row: DATA — the streaming data plane
    # (bigdl_tpu.datapipe). Host-feed (reader -> shuffle -> staged
    # [K,B,...] windows) vs device-feed steps/sec at K=8 for LeNet — the
    # ROADMAP "within ~10% of device-feed" number — and TransformerLM
    # packed-vs-padded tokens/sec with the padding-efficiency gauge
    # values. Skipped on CPU smoke runs unless forced.
    if _row_enabled("BENCH_DATA", platform):
        result.update(_bench_data())
    # sixth tracked row: ZERO — weight-update sharding
    # (bigdl_tpu.parallel.zero). Stage 0 vs 2 vs 3 imgs/sec at K=8
    # scanned windows over a data mesh of all devices, plus
    # opt_state_bytes_per_chip per stage — the n-fold memory reduction
    # and its throughput cost/benefit as scoreboard numbers. Skipped on
    # CPU smoke runs unless forced.
    if _row_enabled("BENCH_ZERO", platform):
        result.update(_bench_zero())
    # seventh tracked row: PRECISION — mixed precision as a policy
    # (bigdl_tpu.precision). ResNet f32 vs bf16_mixed train imgs/sec at
    # K scanned steps, TransformerLM tokens/sec both regimes, and f32
    # vs calibrated-int8 serving imgs/sec with the accuracy delta the
    # serving gate would enforce. Skipped on CPU smoke runs unless
    # forced — bf16 emulates (slowly) on CPU, so the CPU number reports
    # the measured delta, not a win.
    if _row_enabled("BENCH_PRECISION", platform):
        result.update(_bench_precision())
    # eighth tracked row: PROGRAMS — per-model device-side program
    # profiles (bigdl_tpu.telemetry.programs): analytic MFU + HBM
    # bytes + compile time for the resnet50 train window and the
    # eval forward, from XLA's own cost/memory analysis combined with
    # the rates this run just measured. The regression sentinel
    # (tools/regress) tracks these keys. Skipped on CPU smoke runs
    # unless forced — each profile pays one extra AOT compile.
    if _row_enabled("BENCH_PROGRAMS", platform):
        result.update(_bench_programs(
            model, run_chunk, carry,
            jax.random.split(jax.random.fold_in(root, 999), scan),
            batch, scan, imgs_per_sec,
            result.get("resnet50_inference_imgs_per_sec_per_chip")))
    # ninth tracked row: KERNELS — the pallas kernel layer
    # (bigdl_tpu.kernels): attention-program MFU with the flash kernel
    # vs the einsum reference (both registered under kernel= labels in
    # telemetry.programs, the PR-10 gauges as the success metric) and
    # generation decode tokens/sec with the ragged kernel on vs off.
    # Skipped on CPU smoke runs unless forced — the on-leg runs the
    # pallas interpreter.
    if _row_enabled("BENCH_KERNELS", platform):
        result.update(_bench_kernels())
    # tenth tracked row: ELASTIC — preemption-tolerant checkpointing
    # (bigdl_tpu.elastic): the per-checkpoint step-loop stall with the
    # sync (gather + inline write) vs async (snapshot-only) writers,
    # the hidden async write tail, and resume-to-first-step seconds
    # from a committed format-3 checkpoint. Skipped on CPU smoke runs
    # unless forced.
    if _row_enabled("BENCH_ELASTIC", platform):
        result.update(_bench_elastic())
    # eleventh tracked row: FLEET — planet-scale generation serving
    # (bigdl_tpu.fleet): goodput-under-load (tokens/sec at a fixed p99
    # TTFT budget) for 1 vs N replicas behind the router, prefix-cache
    # full-hit TTFT p50 vs the cold prefill p50, and speculative
    # decoding accepted-token rate + tokens/sec on vs off. Skipped on
    # CPU smoke runs unless forced — per-replica warmup compiles
    # dominate CI.
    if _row_enabled("BENCH_FLEET", platform):
        result.update(_bench_fleet())
    # twelfth tracked row: TUNED — the profile-guided autotuner
    # (bigdl_tpu.autotune): one prune-then-measure sweep over the
    # bounded smoke spaces, reporting the tuned winner's steps/sec and
    # decode tokens/sec against the hand-picked default config measured
    # in the SAME sweep (same seed, same windows — the speedup is the
    # autotuner's earned win, not run-to-run noise). Skipped on CPU
    # smoke runs unless forced.
    if _row_enabled("BENCH_TUNED", platform):
        result.update(_bench_tuned())
    # thirteenth tracked row: SLO — the fleet observability plane end
    # to end (telemetry.agg + telemetry.slo): a fleet soak with
    # per-replica PRIVATE registries, merged through
    # aggregate_snapshots, goodput + p99 TTFT read from the MERGED
    # snapshot and judged by one declarative SloSpec. Tracked so a
    # regression in the merge/SLO path (or in fleet goodput itself)
    # trips tools/regress like any perf number. Skipped on CPU smoke
    # runs unless forced.
    if _row_enabled("BENCH_SLO", platform):
        result.update(_bench_slo())
    # fourteenth tracked row: LONGCTX — long-context attention and
    # serving (the blockwise flash kernel past the VMEM budget +
    # chunked prefill): per-S train-step tokens/sec and MFU with the
    # blockwise kernel vs the einsum/bundled-flash fallback, and
    # chunked-prefill TTFT both ways. The fallback legs stop at
    # BENCH_LONGCTX_EINSUM_MAX (default 32K) — past it the O(S^2)
    # reference cannot run at all, which is the row's point. Skipped
    # on CPU smoke runs unless forced.
    if _row_enabled("BENCH_LONGCTX", platform):
        result.update(_bench_longctx())
    # fifteenth tracked row: CONTROL — the SLO-driven control plane
    # under a load ramp (chaos --control leg, faults off): goodput and
    # p99 TTFT while replicas scale 1->N->1, scale-up reaction time,
    # and per-tenant shed fractions. Tracked so a regression in the
    # autoscaler/admission path trips tools/regress like any perf
    # number. Skipped on CPU smoke runs unless forced.
    if _row_enabled("BENCH_CONTROL", platform):
        result.update(_bench_control())
    print(json.dumps(result))
    _maybe_metrics_snapshot(result)


def _bench_inference(model, params, mstate, batch):
    """Eval-mode forward-only ResNet-50 throughput under one scanned
    dispatch (the device serving rate; per-batch host feeds are the
    tunnel's number, not the chip's — BASELINE.md feed note)."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    scan = int(os.environ.get("BENCH_SCAN", 8))
    iters = int(os.environ.get("BENCH_ITERS", 6))

    def scan_body(carry, key):
        x = jax.random.uniform(key, (batch, 3, 224, 224), jnp.float32)
        out, _ = model.apply(params, mstate, x, training=False)
        # carry a scalar data dependency so the chain cannot be elided
        return carry + out[0, 0].astype(jnp.float32), None

    @jax.jit
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    root = jax.random.PRNGKey(7)
    carry = jnp.zeros((), jnp.float32)
    carry, _ = run_chunk(carry, jax.random.split(root, scan))
    float(carry)
    t0 = time.time()
    for i in range(iters):
        carry, _ = run_chunk(carry, jax.random.split(
            jax.random.fold_in(root, i), scan))
    float(carry)
    return batch * scan * iters / (time.time() - t0)


def _bench_generation():
    """TransformerLM generation serving: a burst of seeded ragged
    prompts through the bucketed KV-cache decode engine with
    continuous batching (``bigdl_tpu.generation``). Returns the
    GENERATION row: tokens/sec/chip plus p50/p99 time-to-first-token
    and p50/p99 per-token latency, read from the GenerationService's
    own telemetry histograms so the scoreboard and the service agree
    by construction."""
    import numpy as np

    from bigdl_tpu.generation import GenerationConfig, GenerationService
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.tools.synthetic import seeded_rng
    from bigdl_tpu.utils.random import RandomGenerator

    vocab = int(os.environ.get("BENCH_GEN_VOCAB", 8192))
    hidden = int(os.environ.get("BENCH_GEN_HIDDEN", 512))
    layers = int(os.environ.get("BENCH_GEN_LAYERS", 6))
    max_len = int(os.environ.get("BENCH_GEN_LEN", 512))
    slots = int(os.environ.get("BENCH_GEN_SLOTS", 16))
    n_reqs = int(os.environ.get("BENCH_GEN_REQS", 32))
    max_new = int(os.environ.get("BENCH_GEN_NEW", 32))

    RandomGenerator.set_seed(11)
    model = TransformerLM(vocab_size=vocab, hidden_size=hidden,
                          num_layers=layers, num_heads=8,
                          max_len=max_len).evaluate()
    model.ensure_initialized()
    svc = GenerationService(config=GenerationConfig(
        slots=slots, max_len=max_len, prefill_rows=min(4, slots),
        max_queue=max(n_reqs, 256)))
    svc.load("lm", model)  # warmup: compiles stay out of the timing

    r = seeded_rng(12)
    prompts = [r.randint(1, vocab, r.randint(4, max_len - max_new))
               .astype(np.int32) for _ in range(n_reqs)]
    t0 = time.time()
    streams = [svc.generate("lm", p, max_new_tokens=max_new)
               for p in prompts]
    total = sum(len(s.result()) for s in streams)
    dt = time.time() - t0
    m = svc.metrics("lm")
    svc.shutdown()
    row = {
        "transformerlm_generation_tokens_per_sec_per_chip":
            round(total / dt, 1),
        "generation_requests": n_reqs,
        "generation_compiles": int(m["compile_count"]),
    }
    for key in ("ttft_ms_p50", "ttft_ms_p99",
                "token_ms_p50", "token_ms_p99"):
        if key in m:
            row[f"generation_{key}"] = round(float(m[key]), 3)
    return row


def _bench_fleet():
    """FLEET row: the planet-scale serving numbers (bigdl_tpu.fleet).

    Leg 1 — goodput under load: the same seeded burst through a
    1-replica and an N-replica router; goodput = tokens/sec times the
    fraction of ACCEPTED requests meeting the p99 TTFT budget (shed
    requests failed fast and typed — that is the router working).
    Leg 2 — prefix/KV reuse: one service with the prefix cache on,
    the same prompts twice; cold p50 TTFT pays the prefill, hit p50
    pays one seed-copy + decode step (the acceptance bound: hit p50
    within 2x the decode-step p50).  Leg 3 — speculative decoding:
    the same prompts through target-only generation vs the
    draft-propose/target-verify decoder; accepted-token rate decides
    whether the draft pays for itself."""
    import numpy as np

    import bigdl_tpu.telemetry as telemetry
    from bigdl_tpu.fleet import (FleetRouter, SpeculativeConfig,
                                 SpeculativeDecoder, build_replicas,
                                 run_fleet_soak)
    from bigdl_tpu.generation import GenerationConfig, GenerationService
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.tools.synthetic import seeded_rng
    from bigdl_tpu.utils.random import RandomGenerator

    vocab = int(os.environ.get("BENCH_FLEET_VOCAB", 1024))
    hidden = int(os.environ.get("BENCH_FLEET_HIDDEN", 128))
    layers = int(os.environ.get("BENCH_FLEET_LAYERS", 2))
    heads = int(os.environ.get("BENCH_FLEET_HEADS", 4))
    max_len = int(os.environ.get("BENCH_FLEET_LEN", 64))
    slots = int(os.environ.get("BENCH_FLEET_SLOTS", 4))
    n_replicas = int(os.environ.get("BENCH_FLEET_REPLICAS", 2))
    n_reqs = int(os.environ.get("BENCH_FLEET_REQS", 24))
    max_new = int(os.environ.get("BENCH_FLEET_NEW", 8))
    budget_ms = float(os.environ.get("BENCH_FLEET_TTFT_BUDGET_MS",
                                     2000.0))
    row = {"fleet_replicas": n_replicas,
           "fleet_ttft_budget_ms": budget_ms}

    # -- leg 1: goodput under load, 1 vs N replicas -------------------
    for tag, n in (("1r", 1), ("nr", n_replicas)):
        router = FleetRouter(build_replicas(
            n, seed=21, vocab=vocab, hidden=hidden, layers=layers,
            heads=heads, slots=slots, max_len=max_len, max_queue=8,
            metrics=telemetry.MetricsRegistry()))
        rep = run_fleet_soak(router=router, requests=n_reqs,
                             threads=4, max_new=max_new,
                             prompt_len=max_len // 4, seed=22,
                             open_breaker_on=None,
                             ttft_budget_ms=budget_ms,
                             token_budget_ms=budget_ms)
        router.shutdown()
        row[f"fleet_goodput_tokens_per_sec_{tag}"] = round(
            rep["tokens_per_sec"]
            * rep["ttft_within_budget_fraction"], 2)
        row[f"fleet_ttft_ms_p99_{tag}"] = rep.get("ttft_ms_p99", 0.0)
    if row["fleet_goodput_tokens_per_sec_1r"]:
        row["fleet_goodput_scaling"] = round(
            row["fleet_goodput_tokens_per_sec_nr"]
            / row["fleet_goodput_tokens_per_sec_1r"], 3)

    # -- leg 2: prefix-cache hit vs cold TTFT -------------------------
    RandomGenerator.set_seed(23)
    model = TransformerLM(vocab_size=vocab, hidden_size=hidden,
                          num_layers=layers, num_heads=heads,
                          max_len=max_len).evaluate()
    model.ensure_initialized()
    svc = GenerationService(config=GenerationConfig(
        slots=slots, max_len=max_len, prefill_rows=min(2, slots),
        # this row MEASURES the prefix cache, so the cache size is part
        # of the experiment, not a tunable
        prefix_cache_bytes=256 << 20))  # bigdl: disable=hardcoded-tuned-constant
    svc.load("lm", model)
    r = seeded_rng(24)
    prompts = [r.randint(1, vocab, max_len - max_new - 1)
               .astype(np.int32) for _ in range(8)]
    cold_ttft, hit_ttft = [], []
    for leg in (cold_ttft, hit_ttft):
        for p in prompts:
            s = svc.generate("lm", p, max_new_tokens=max_new)
            s.result(120)
            leg.append(s.ttft_ms)
    m = svc.metrics("lm")
    assert m["prefix_hits"] >= len(prompts), m
    svc.shutdown()
    row.update({
        "fleet_prefix_cold_ttft_ms_p50": round(
            float(np.median(cold_ttft)), 3),
        "fleet_prefix_hit_ttft_ms_p50": round(
            float(np.median(hit_ttft)), 3),
        "fleet_token_ms_p50": round(float(m["token_ms_p50"]), 3),
        "fleet_prefix_ttft_speedup": round(
            float(np.median(cold_ttft) / max(np.median(hit_ttft),
                                             1e-9)), 2),
    })

    # -- leg 3: speculative decoding on vs off ------------------------
    RandomGenerator.set_seed(25)
    draft = TransformerLM(vocab_size=vocab, hidden_size=hidden // 2,
                          num_layers=1, num_heads=heads,
                          max_len=max_len).evaluate()
    draft.ensure_initialized()
    spec_prompts = [r.randint(1, vocab, max_len // 4).astype(np.int32)
                    for _ in range(slots)]
    spec_new = min(max_new, max_len // 2)
    svc_off = GenerationService(config=GenerationConfig(
        slots=slots, max_len=max_len, prefill_rows=min(2, slots)))
    svc_off.load("lm", model)
    t0 = time.time()
    streams = [svc_off.generate("lm", p, max_new_tokens=spec_new)
               for p in spec_prompts]
    off_tokens = sum(len(s.result(120)) for s in streams)
    off_dt = time.time() - t0
    svc_off.shutdown()
    dec = SpeculativeDecoder(model, draft, SpeculativeConfig(
        k=int(os.environ.get("BENCH_FLEET_SPEC_K", 4)), slots=slots,
        max_len=max_len))
    # full-depth warmup: compiles every verify/decode rung the timed
    # run will touch (attend buckets grow with the sequence)
    dec.generate(spec_prompts, spec_new)
    t0 = time.time()
    outs, stats = dec.generate(spec_prompts, spec_new)
    on_dt = time.time() - t0
    row.update({
        "fleet_spec_accept_rate": round(stats["accept_rate"], 4),
        "fleet_spec_tokens_per_sec_off": round(off_tokens / off_dt, 1),
        "fleet_spec_tokens_per_sec_on": round(
            stats["tokens"] / on_dt, 1),
        "fleet_spec_speedup": round(
            (stats["tokens"] / on_dt) / (off_tokens / off_dt), 3),
    })
    return row


def _bench_slo():
    """SLO row: fleet soak goodput + p99 TTFT **from the merged
    cross-process snapshot** (telemetry.agg), judged by one
    declarative SloSpec (telemetry.slo). Each replica serves from its
    own PRIVATE registry — the merge is load-bearing, not cosmetic:
    a broken aggregator shows up here as a zero/missing p99 and
    ``slo_passed`` drops to 0."""
    import bigdl_tpu.telemetry as telemetry
    from bigdl_tpu.fleet import (FleetRouter, build_replicas,
                                 run_fleet_soak)
    from bigdl_tpu.telemetry import agg
    from bigdl_tpu.telemetry import slo as slo_mod

    n_replicas = int(os.environ.get("BENCH_SLO_REPLICAS", 2))
    n_reqs = int(os.environ.get("BENCH_SLO_REQS", 24))
    max_new = int(os.environ.get("BENCH_SLO_NEW", 6))
    budget_ms = float(os.environ.get("BENCH_SLO_TTFT_BUDGET_MS",
                                     5000.0))

    # metrics=None -> every replica's GenerationService creates its
    # own registry; the router keeps a separate one of its own
    reps = build_replicas(n_replicas, seed=31, max_queue=8,
                          metrics=None)
    router = FleetRouter(reps, metrics=telemetry.MetricsRegistry())
    try:
        soak = run_fleet_soak(router=router, requests=n_reqs,
                              threads=4, max_new=max_new, seed=32,
                              open_breaker_on=None,
                              ttft_budget_ms=budget_ms)
    finally:
        router.shutdown(drain=True)

    sources = [({"replica": r.name},
                r.service.metrics_registry.snapshot(True))
               for r in reps]
    sources.append(({"replica": "router"},
                    router.metrics_registry.snapshot(True)))
    merged = agg.aggregate_snapshots(sources)
    bad = agg.check_merge_invariant(sources, merged)
    spec = slo_mod.SloSpec.parse(
        f"p99_ttft: serving/generation/ttft_ms.p99 <= {budget_ms};"
        "goodput: goodput_tokens_per_sec >= 0.001")
    rep = slo_mod.evaluate(
        spec, merged,
        {"goodput_tokens_per_sec": soak["goodput_tokens_per_sec"]})
    by = {v.objective.name: v.value for v in rep.verdicts}
    return {
        "slo_goodput_tokens_per_sec": round(
            soak["goodput_tokens_per_sec"], 2),
        "slo_ttft_ms_p99": round(by.get("p99_ttft") or 0.0, 3),
        "slo_passed": int(rep.passed and soak["passed"] and not bad),
    }


def _bench_control():
    """CONTROL row: the chaos ``--control`` load-ramp leg run
    fault-free — goodput and p99 TTFT while the autoscaler takes the
    fleet 1->N->1 under a two-tenant burst, the scale-up reaction
    time, and each tenant's shed fraction. ``control_passed`` drops
    to 0 when the leg's invariants (typed-only sheds, zero hangs,
    ramp reached N, drained back to 1) break.

    Key naming is deliberate for tools/regress's classifier:
    ``*_per_sec`` higher-is-better, ``*_ms`` lower-is-better, and the
    shed fractions use the unclassified ``_frac_`` spelling — a shed
    fraction moving is context, not a regression by itself."""
    from bigdl_tpu.tools.chaos import run_control

    max_replicas = int(os.environ.get("BENCH_CONTROL_REPLICAS", 3))
    leg = run_control(max_replicas=max_replicas, inject=False)
    tenants = leg.get("tenants") or {}
    return {
        "control_goodput_tokens_per_sec": round(
            leg["goodput_tokens_per_sec"], 2),
        "control_ttft_ms_p99": round(
            (leg.get("latency") or {}).get("ramp_ttft_ms_p99")
            or 0.0, 3),
        "control_scaleup_reaction_ms": round(
            leg.get("scaleup_reaction_ms") or 0.0, 1),
        "control_shed_frac_gold": (
            tenants.get("gold") or {}).get("shed_fraction", 0.0),
        "control_shed_frac_bronze": (
            tenants.get("bronze") or {}).get("shed_fraction", 0.0),
        "control_passed": int(leg["passed"]),
    }


def _bench_longctx():
    """LONGCTX row: what the long-context stack buys, as
    sentinel-tracked numbers at S in BENCH_LONGCTX_SEQS (default
    8K/32K/128K).

    Leg 1 — training attention: one fused fwd+bwd causal attention
    step (``jit(value_and_grad)``, so the custom-VJP backward is the
    program measured) per S, blockwise flash kernel on vs the
    einsum/bundled-flash reference, each registered in
    ``telemetry.programs`` with the kernel= label decided by trace
    EVIDENCE — tokens/sec + MFU both ways and the speedup. Past
    ``BENCH_LONGCTX_EINSUM_MAX`` the quadratic reference is not run
    (it cannot fit); the blockwise numbers stand alone, which is the
    row's point. Leg 2 — serving: TTFT of an ~S-token prompt through
    chunked prefill (fixed BENCH_LONGCTX_CHUNK-wide chunks through the
    existing bucket rungs), kernels on vs off under the same chunking,
    with the prefill chunk count and compile count carried so the
    <=2-programs-per-bucket bound stays checkable. On CPU the
    kernel-on legs run the pallas interpreter, so CPU numbers document
    equivalence overhead, not a win — shrink BIGDL_VMEM_BUDGET_MB to
    steer small smoke shapes down the blockwise route."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu import kernels
    from bigdl_tpu.generation import GenerationConfig, GenerationService
    from bigdl_tpu.kernels.dispatch import taken_in_thread
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.nn.attention import dot_product_attention
    from bigdl_tpu.telemetry import programs
    from bigdl_tpu.tools.synthetic import seeded_rng
    from bigdl_tpu.utils.random import RandomGenerator

    seqs = [int(s) for s in os.environ.get(
        "BENCH_LONGCTX_SEQS", "8192,32768,131072").split(",")]
    b = int(os.environ.get("BENCH_LONGCTX_BATCH", 1))
    heads = int(os.environ.get("BENCH_LONGCTX_HEADS", 8))
    hd = int(os.environ.get("BENCH_LONGCTX_HEAD_DIM", 64))
    einsum_max = int(os.environ.get("BENCH_LONGCTX_EINSUM_MAX", 32768))
    chunk = int(os.environ.get("BENCH_LONGCTX_CHUNK", 2048))
    vocab = int(os.environ.get("BENCH_LONGCTX_VOCAB", 8192))
    hidden = int(os.environ.get("BENCH_LONGCTX_HIDDEN", 512))
    layers = int(os.environ.get("BENCH_LONGCTX_LAYERS", 2))
    max_new = int(os.environ.get("BENCH_LONGCTX_NEW", 8))
    iters = int(os.environ.get("BENCH_ITERS", 6))
    reg = programs.registry()
    row = {"longctx_einsum_max": einsum_max,
           "longctx_prefill_chunk": chunk}

    def attn_leg(s, tag, cfg):
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(41 + s % 97), 3)
        q = jax.random.normal(kq, (b, heads, s, hd), jnp.float32)
        k = jax.random.normal(kk, (b, heads, s, hd), jnp.float32)
        v = jax.random.normal(kv, (b, heads, s, hd), jnp.float32)
        with kernels.use(cfg):
            fn = jax.jit(jax.value_and_grad(
                lambda q_, k_, v_: dot_product_attention(
                    q_, k_, v_, causal=True).sum(), argnums=(0, 1, 2)))
            taken_before = taken_in_thread()
            t0 = time.perf_counter()
            compiled = fn.lower(q, k, v).compile()
            compile_s = time.perf_counter() - t0
            taken = int(taken_in_thread() > taken_before)
            name = f"bench/longctx/s{s}/{tag}"
            reg.register(name, "train", compiled=compiled,
                         compile_s=compile_s, items_per_call=b * s,
                         kernel="pallas" if taken else "reference")
            jax.block_until_ready(compiled(q, k, v))  # warm
            t0 = time.perf_counter()
            out = None
            for _ in range(iters):
                out = compiled(q, k, v)
            jax.block_until_ready(out)
            rate = b * s * iters / (time.perf_counter() - t0)
            prof = reg.record_rate(name, rate)
            mfu = prof.mfu if prof is not None else None
            return rate, (mfu or 0.0), taken

    def ttft_leg(s, cfg):
        with kernels.use(cfg):
            RandomGenerator.set_seed(43)
            model = TransformerLM(vocab_size=vocab, hidden_size=hidden,
                                  num_layers=layers, num_heads=heads,
                                  max_len=s).evaluate()
            model.ensure_initialized()
            svc = GenerationService(config=GenerationConfig(
                slots=2, max_len=s, prefill_rows=2,
                prefill_chunk=chunk))
            svc.load("longlm", model)  # warmup compiles off the clock
            r = seeded_rng(44)
            prompt = r.randint(1, vocab, s - max_new).astype(np.int32)
            stream = svc.generate("longlm", prompt,
                                  max_new_tokens=max_new)
            stream.result()
            ttft = stream.ttft_ms
            m = svc.metrics("longlm")
            svc.shutdown()
            return ttft, int(m.get("prefill_chunks", 0)), \
                int(m["compile_count"])

    for s in seqs:
        rate_on, mfu_on, taken = attn_leg(
            s, "blockwise", kernels.KernelConfig.all_on())
        row[f"longctx_s{s}_tokens_per_sec_blockwise"] = round(rate_on, 1)
        row[f"longctx_s{s}_mfu_blockwise"] = round(mfu_on, 4)
        row[f"longctx_s{s}_flash_taken"] = taken
        if s <= einsum_max:
            rate_off, mfu_off, _ = attn_leg(
                s, "einsum", kernels.KernelConfig.off())
            row[f"longctx_s{s}_tokens_per_sec_einsum"] = round(
                rate_off, 1)
            row[f"longctx_s{s}_mfu_einsum"] = round(mfu_off, 4)
            row[f"longctx_s{s}_blockwise_speedup"] = round(
                rate_on / rate_off, 3)
        ttft, chunks, compiles = ttft_leg(s, kernels.KernelConfig.all_on())
        row[f"longctx_s{s}_ttft_ms"] = round(ttft, 3)
        row[f"longctx_s{s}_prefill_chunks"] = chunks
        row[f"longctx_s{s}_generation_compiles"] = compiles
        if s <= einsum_max:
            ttft_ref, _, _ = ttft_leg(s, kernels.KernelConfig.off())
            row[f"longctx_s{s}_ttft_ms_einsum"] = round(ttft_ref, 3)
    return row


def _bench_data():
    """DATA row: how fast the streaming data plane feeds the chip.

    Leg 1 — LeNet at K=8: device-feed (HBM-cached ``batch_fn`` inside
    the scan, the feed ceiling) vs host-feed (datapipe reader ->
    seeded shuffle -> SampleToMiniBatch -> ``[K, B, ...]`` staged
    windows consumed by the same scanned step). Leg 2 — TransformerLM
    on ragged documents: packed slabs (segment masks) vs pad-to-max
    rows through the identical train step; tokens/sec counts REAL
    tokens, so the packed win is the padding it no longer computes.
    """
    import functools

    import jax
    import numpy as np
    from jax import lax

    import bigdl_tpu.nn as nn
    from bigdl_tpu import datapipe as dp
    from bigdl_tpu.dataset.device_dataset import DeviceCachedArrayDataSet
    from bigdl_tpu.models import LeNet5, TransformerLM
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.tools.synthetic import seeded_rng
    from bigdl_tpu.utils.random import RandomGenerator

    k = int(os.environ.get("BENCH_DATA_K", 8))
    iters = int(os.environ.get("BENCH_ITERS", 6))
    batch = int(os.environ.get("BENCH_DATA_BATCH", 128))
    row = {}

    def window_runner(step):
        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def run(p, o, m, keys, xs, ys):
            def body(carry, sl):
                p, o, m = carry
                key, x, y = sl
                p, o, m, loss = step(p, o, m, key, 0.05, x, y)
                return (p, o, m), loss
            (p, o, m), losses = lax.scan(body, (p, o, m), (keys, xs, ys))
            return p, o, m, losses
        return run

    rng = seeded_rng(6)
    n_pool = max(4 * batch, 512)
    imgs = rng.rand(n_pool, 1, 28, 28).astype(np.float32)
    labels = (rng.randint(0, 10, n_pool) + 1).astype(np.float32)

    def lenet_setup():
        RandomGenerator.set_seed(5)
        model = LeNet5(10).training()
        model.ensure_initialized()
        optim = SGD(learning_rate=0.05)
        step = build_train_step(model, nn.ClassNLLCriterion(), optim)
        return step, (model.get_parameters(),
                      optim.init_state(model.get_parameters()),
                      model.get_state())

    def lenet_host_leg() -> float:
        step, carry = lenet_setup()
        run = window_runner(step)
        root = jax.random.PRNGKey(2)
        pipe = (dp.Pipeline(dp.ArrayRecordReader(imgs, labels, seed=1))
                .shuffle(buffer_size=4 * batch, seed=2)
                .batch(batch, drop_remainder=True))
        staged = pipe.staged(k=k, loop=True)
        try:
            done = -1  # one warmup window, then `iters` timed ones
            t0 = None
            while done < iters:
                keys = jax.random.split(jax.random.fold_in(root, done + 1), k)
                b = next(staged)
                p, o, m, losses = run(*carry, keys, b.input, b.target)
                carry = (p, o, m)
                float(losses.sum())  # window boundary: the host sync
                done += 1
                if t0 is None:
                    t0 = time.time()
            dt = time.time() - t0
        finally:
            staged.close()
        return k * iters / dt

    def lenet_dev_leg() -> float:
        import jax.numpy as jnp
        step, carry = lenet_setup()
        ds = DeviceCachedArrayDataSet(
            (imgs * 255).astype(np.uint8), labels, batch,
            crop=(28, 28), flip=False, mean=(0.0,), std=(255.0,))
        root = jax.random.PRNGKey(2)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run(carry, keys):
            def body(c, key):
                p, o, m, ep, pos = c
                kb, kr = jax.random.split(key)
                x, y = ds.batch_fn(kb, epoch=ep, pos=pos)
                p, o, m, loss = step(p, o, m, kr, 0.05, x, y)
                pos = pos + batch
                return (p, o, m, ep + pos // ds.n, pos % ds.n), loss
            return lax.scan(body, carry, keys)
        carry = carry + (jnp.int32(0), jnp.int32(0))
        done = -1
        t0 = None
        while done < iters:
            keys = jax.random.split(jax.random.fold_in(root, done + 1), k)
            carry, losses = run(carry, keys)
            float(losses.sum())
            done += 1
            if t0 is None:
                t0 = time.time()
        return k * iters / (time.time() - t0)

    dev = lenet_dev_leg()
    host = lenet_host_leg()
    row["data_window_k"] = k
    row["data_lenet_devfeed_steps_per_sec"] = round(dev, 2)
    row["data_lenet_hostfeed_steps_per_sec"] = round(host, 2)
    row["data_hostfeed_fraction_of_devfeed"] = round(host / dev, 3)

    # ---- TransformerLM: packed slabs vs pad-to-max rows ----------------
    vocab = int(os.environ.get("BENCH_DATA_VOCAB", 4096))
    seq = int(os.environ.get("BENCH_DATA_SEQ", 256))
    rows_b = int(os.environ.get("BENCH_DATA_ROWS", 8))
    r2 = seeded_rng(7)
    docs = [r2.randint(1, vocab, int(n)).astype(np.int32)
            for n in r2.randint(8, seq // 2, 256)]
    lengths = [len(d) - 1 for d in docs]
    packed_arrays = dp.pack_documents(docs, seq)  # packed once: the
    # timed leg and the efficiency number must describe the same slabs

    def tlm_leg(packed: bool) -> float:
        RandomGenerator.set_seed(9)
        model = TransformerLM(vocab_size=vocab, hidden_size=256,
                              num_layers=4, num_heads=8,
                              max_len=seq).training()
        model.ensure_initialized()
        optim = SGD(learning_rate=0.1)
        crit = nn.SequenceCrossEntropyCriterion(ignore_index=-1)
        step = build_train_step(model, crit, optim)
        params = model.get_parameters()
        mstate = model.get_state()
        opt_state = optim.init_state(params)
        if packed:
            toks, segs, pos, tgt = packed_arrays
        else:
            packer = dp.LengthBucketBatcher([seq], len(docs))
            (mb,) = list(packer(iter(docs), 0))
            toks, segs, pos = mb.input
            tgt = mb.target
        n_rows = (len(toks) // rows_b) * rows_b
        if n_rows == 0:
            raise ValueError(
                f"BENCH_DATA_ROWS={rows_b} exceeds the {len(toks)} "
                f"{'packed' if packed else 'padded'} rows the corpus "
                "yields; lower BENCH_DATA_ROWS")
        batches = [([toks[i:i + rows_b], segs[i:i + rows_b],
                     pos[i:i + rows_b]], tgt[i:i + rows_b],
                    int((segs[i:i + rows_b] > 0).sum()))
                   for i in range(0, n_rows, rows_b)]
        carry = (params, opt_state, mstate)
        real_tokens = 0
        t0 = None
        for it in range(iters + 1):
            for x, y, n_real in batches:
                p, o, m, loss = step(*carry, RandomGenerator.next_key(),
                                     0.1, x, y)
                carry = (p, o, m)
                if it > 0:
                    real_tokens += n_real
            float(loss)
            if t0 is None:
                t0 = time.time()  # first pass was compile+warmup
        return real_tokens / (time.time() - t0)

    row["data_tlm_packed_tokens_per_sec"] = round(tlm_leg(True), 1)
    row["data_tlm_padded_tokens_per_sec"] = round(tlm_leg(False), 1)
    row["data_padding_efficiency_padded"] = round(
        dp.padding_efficiency(lengths, seq), 4)
    packed_segs = packed_arrays[1]
    row["data_padding_efficiency_packed"] = round(
        float((packed_segs > 0).mean()), 4) if len(packed_segs) else 1.0
    return row


def _bench_zero():
    """ZERO row: ResNet training at ZeRO stage 0 vs 2 vs 3 over a
    data-parallel mesh of every available device, K scanned steps per
    dispatch (the windowed-driver regime where the collectives overlap
    the neighbouring steps' compute). Reports imgs/sec and the per-chip
    optimizer-state bytes each stage leaves resident — the measured
    form of the ZeRO memory math in docs/performance.md."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import ResNet
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.parallel import (ZeroConfig, data_parallel_mesh,
                                    place_zero_state, tree_bytes_per_chip)
    from bigdl_tpu.utils.random import RandomGenerator

    scan = int(os.environ.get("BENCH_SCAN", 8))
    iters = int(os.environ.get("BENCH_ITERS", 6))
    mesh = data_parallel_mesh()
    ndev = mesh.shape["data"]
    batch = int(os.environ.get("BENCH_ZERO_BATCH", 16 * ndev))
    batch = max(ndev, batch - batch % ndev)
    depth = int(os.environ.get("BENCH_ZERO_DEPTH", 20))
    repl = NamedSharding(mesh, P())
    bsh = NamedSharding(mesh, P("data"))
    row = {"zero_window_k": scan, "zero_devices": ndev,
           "zero_batch": batch}

    def leg(stage):
        RandomGenerator.set_seed(13)
        model = ResNet(10, depth=depth, dataset="CIFAR10").training()
        model.ensure_initialized()
        optim = SGD(learning_rate=0.1, momentum=0.9)
        cfg = ZeroConfig(stage=stage) if stage else None
        params = model.get_parameters()
        opt_state = optim.init_state(params)
        params, opt_state = place_zero_state(params, opt_state, mesh,
                                             cfg)
        mstate = jax.device_put(model.get_state(), repl)
        step = build_train_step(model, nn.CrossEntropyCriterion(), optim,
                                zero=cfg, mesh=mesh)

        def scan_body(carry, key):
            params, opt_state, mstate = carry
            kx, ky, kr = jax.random.split(key, 3)
            x = jax.lax.with_sharding_constraint(
                jax.random.uniform(kx, (batch, 3, 32, 32), jnp.float32),
                bsh)
            y = jax.lax.with_sharding_constraint(
                jax.random.randint(ky, (batch,), 1, 11)
                .astype(jnp.float32), bsh)
            params, opt_state, mstate, loss = step(
                params, opt_state, mstate, kr, 0.1, x, y)
            return (params, opt_state, mstate), loss

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_chunk(carry, keys):
            return lax.scan(scan_body, carry, keys)

        opt_bytes = tree_bytes_per_chip(opt_state)
        root = jax.random.PRNGKey(3)
        carry = (params, opt_state, mstate)
        carry, losses = run_chunk(carry, jax.random.split(root, scan))
        float(losses.sum())  # compile + warmup outside the clock
        t0 = time.time()
        for i in range(iters):
            carry, losses = run_chunk(
                carry, jax.random.split(jax.random.fold_in(root, i + 1),
                                        scan))
        float(losses.sum())
        return batch * scan * iters / (time.time() - t0), opt_bytes

    for stage in (0, 2, 3):
        rate, opt_bytes = leg(stage)
        row[f"zero_stage{stage}_imgs_per_sec"] = round(rate, 2)
        row[f"zero_stage{stage}_opt_state_bytes_per_chip"] = opt_bytes
    row["zero_opt_state_reduction_stage2"] = round(
        row["zero_stage0_opt_state_bytes_per_chip"]
        / max(1, row["zero_stage2_opt_state_bytes_per_chip"]), 2)
    return row


def _bench_precision():
    """PRECISION row: what the precision policy buys, as scoreboard
    numbers.

    Leg 1 — ResNet training (depth BENCH_PREC_DEPTH; 50 = the ImageNet
    north-star, smoke tests shrink it) under ``f32`` vs ``bf16_mixed``
    at K scanned steps per dispatch: identical program, identical data
    keys, only the policy differs — the ratio is the bf16 win. Leg 2 —
    TransformerLM train tokens/sec under both regimes. Leg 3 — serving:
    f32 forward vs CALIBRATED int8 (activation scales from
    ``precision.calibrate`` over real calibration batches), imgs/sec
    plus the top-1 agreement delta measured by the same ``AccuracyGate``
    the registry's quantized loads enforce — the delta in this row is
    the number the gate would compare against its bound."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import ResNet, TransformerLM
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.precision import AccuracyGate, PrecisionPolicy
    from bigdl_tpu.utils.random import RandomGenerator

    scan = int(os.environ.get("BENCH_SCAN", 8))
    iters = int(os.environ.get("BENCH_ITERS", 6))
    depth = int(os.environ.get("BENCH_PREC_DEPTH", 50))
    batch = int(os.environ.get("BENCH_PREC_BATCH", 64))
    dataset = "ImageNet" if depth >= 50 else "CIFAR10"
    classes = 1000 if depth >= 50 else 10
    hw = 224 if depth >= 50 else 32
    row = {"precision_window_k": scan, "precision_resnet_depth": depth,
           "precision_batch": batch}

    def resnet_leg(policy) -> float:
        RandomGenerator.set_seed(17)
        model = ResNet(classes, depth=depth, dataset=dataset).training()
        model.ensure_initialized()
        optim = SGD(learning_rate=0.1, momentum=0.9)
        params = model.get_parameters()
        opt_state = optim.init_state(params)
        step = build_train_step(model, nn.CrossEntropyCriterion(), optim,
                                precision=policy)

        def scan_body(carry, key):
            params, opt_state, mstate = carry
            kx, ky, kr = jax.random.split(key, 3)
            x = jax.random.uniform(kx, (batch, 3, hw, hw), jnp.float32)
            y = jax.random.randint(ky, (batch,), 1, classes + 1) \
                .astype(jnp.float32)
            params, opt_state, mstate, loss = step(
                params, opt_state, mstate, kr, 0.1, x, y)
            return (params, opt_state, mstate), loss

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_chunk(carry, keys):
            return lax.scan(scan_body, carry, keys)

        root = jax.random.PRNGKey(4)
        carry = (params, opt_state, model.get_state())
        carry, losses = run_chunk(carry, jax.random.split(root, scan))
        float(losses.sum())  # compile + warmup outside the clock
        t0 = time.time()
        for i in range(iters):
            carry, losses = run_chunk(
                carry, jax.random.split(jax.random.fold_in(root, i + 1),
                                        scan))
        float(losses.sum())
        return batch * scan * iters / (time.time() - t0)

    f32 = resnet_leg(PrecisionPolicy.f32())
    bf16 = resnet_leg(PrecisionPolicy.bf16_mixed())
    row["precision_resnet_f32_imgs_per_sec"] = round(f32, 2)
    row["precision_resnet_bf16_imgs_per_sec"] = round(bf16, 2)
    row["precision_resnet_bf16_speedup"] = round(bf16 / f32, 3)

    # ---- TransformerLM tokens/sec, both regimes ------------------------
    vocab = int(os.environ.get("BENCH_PREC_VOCAB", 4096))
    hidden = int(os.environ.get("BENCH_PREC_HIDDEN", 256))
    layers = int(os.environ.get("BENCH_PREC_LAYERS", 4))
    seq = int(os.environ.get("BENCH_PREC_SEQ", 256))
    lm_batch = int(os.environ.get("BENCH_PREC_LM_BATCH", 8))

    def tlm_leg(policy) -> float:
        RandomGenerator.set_seed(19)
        model = TransformerLM(vocab_size=vocab, hidden_size=hidden,
                              num_layers=layers, num_heads=8,
                              max_len=seq).training()
        model.ensure_initialized()
        optim = SGD(learning_rate=0.1)
        crit = nn.SequenceCrossEntropyCriterion(ignore_index=-1)
        step = build_train_step(model, crit, optim, precision=policy)
        params = model.get_parameters()
        opt_state = optim.init_state(params)

        def scan_body(carry, key):
            params, opt_state, mstate = carry
            kx, kr = jax.random.split(key)
            toks = jax.random.randint(kx, (lm_batch, seq), 1, vocab)
            tgt = jnp.roll(toks, -1, axis=1)
            params, opt_state, mstate, loss = step(
                params, opt_state, mstate, kr, 0.1, toks, tgt)
            return (params, opt_state, mstate), loss

        @functools.partial(jax.jit, donate_argnums=(0,))
        def run_chunk(carry, keys):
            return lax.scan(scan_body, carry, keys)

        root = jax.random.PRNGKey(5)
        carry = (params, opt_state, model.get_state())
        carry, losses = run_chunk(carry, jax.random.split(root, scan))
        float(losses.sum())
        t0 = time.time()
        for i in range(iters):
            carry, losses = run_chunk(
                carry, jax.random.split(jax.random.fold_in(root, i + 1),
                                        scan))
        float(losses.sum())
        return lm_batch * seq * scan * iters / (time.time() - t0)

    tf32 = tlm_leg(PrecisionPolicy.f32())
    tbf16 = tlm_leg(PrecisionPolicy.bf16_mixed())
    row["precision_tlm_f32_tokens_per_sec"] = round(tf32, 1)
    row["precision_tlm_bf16_tokens_per_sec"] = round(tbf16, 1)
    row["precision_tlm_bf16_speedup"] = round(tbf16 / tf32, 3)

    # ---- serving: f32 vs calibrated int8 -------------------------------
    from bigdl_tpu.nn.quantized import quantize
    from bigdl_tpu.precision.calibrate import collect_activation_scales
    from bigdl_tpu.tools.synthetic import seeded_rng

    RandomGenerator.set_seed(23)
    fmodel = ResNet(classes, depth=depth, dataset=dataset).evaluate()
    fmodel.ensure_initialized()
    r = seeded_rng(24)
    calib = [r.rand(min(batch, 16), 3, hw, hw).astype(np.float32)
             for _ in range(2)]
    scales = collect_activation_scales(fmodel, calib)
    qmodel = quantize(fmodel, act_scales=scales)

    def serve_leg(model) -> float:
        params, mstate = model.get_parameters(), model.get_state()

        def scan_body(carry, key):
            x = jax.random.uniform(key, (batch, 3, hw, hw), jnp.float32)
            out, _ = model.apply(params, mstate, x, training=False)
            return carry + out[0, 0].astype(jnp.float32), None

        @jax.jit
        def run_chunk(carry, keys):
            return lax.scan(scan_body, carry, keys)

        root = jax.random.PRNGKey(6)
        carry = jnp.zeros((), jnp.float32)
        carry, _ = run_chunk(carry, jax.random.split(root, scan))
        float(carry)
        t0 = time.time()
        for i in range(iters):
            carry, _ = run_chunk(carry, jax.random.split(
                jax.random.fold_in(root, i + 1), scan))
        float(carry)
        return batch * scan * iters / (time.time() - t0)

    sf32 = serve_leg(fmodel)
    sint8 = serve_leg(qmodel)
    # the SAME gate the registry's quantized loads enforce; agreement
    # mode (no labels) — delta is the top-1 disagreement rate
    gate = AccuracyGate(
        inputs=r.rand(int(os.environ.get("BENCH_PREC_GATE_N", 64)),
                      3, hw, hw).astype(np.float32),
        max_delta=float(os.environ.get("BENCH_PREC_GATE", 0.02)))
    delta = gate.evaluate(fmodel, qmodel)
    row["precision_serving_f32_imgs_per_sec"] = round(sf32, 2)
    row["precision_serving_int8_imgs_per_sec"] = round(sint8, 2)
    row["precision_serving_int8_speedup"] = round(sint8 / sf32, 3)
    row["precision_int8_accuracy_delta"] = round(delta, 4)
    row["precision_int8_gate_max_delta"] = gate.max_delta
    return row


def _bench_programs(model, run_chunk, carry, keys, batch, scan,
                    train_rate, infer_rate):
    """PROGRAMS row: register the resnet50 train window (and eval
    forward) in the program-profile registry and combine the analytic
    FLOPs/HBM numbers with the rates the earlier rows measured —
    per-model MFU + HBM bytes as sentinel-tracked scoreboard keys."""
    import time as _time

    import jax

    from bigdl_tpu.optim.optimizer import build_eval_step
    from bigdl_tpu.telemetry import programs

    reg = programs.registry()
    row = {}

    t0 = _time.perf_counter()
    compiled = run_chunk.lower(carry, keys).compile()
    compile_s = _time.perf_counter() - t0
    reg.register("bench/resnet50/train_window", "train",
                 compiled=compiled, compile_s=compile_s,
                 scan_length=scan, items_per_call=batch * scan,
                 donation="carry")
    prof = reg.record_rate("bench/resnet50/train_window", train_rate)
    row["programs_resnet50_train_hbm_bytes"] = int(prof.hbm_bytes)
    row["programs_resnet50_train_flops_per_img"] = round(
        prof.flops / (batch * scan), 1)
    row["programs_resnet50_train_compile_s"] = round(compile_s, 3)
    if prof.mfu is not None:
        row["programs_resnet50_train_mfu"] = round(prof.mfu, 4)
        row["programs_resnet50_train_achieved_tfs"] = round(
            prof.achieved_tfs, 3)

    # eval forward at the same batch (params/state ride the final carry
    # — the originals were donated into the train chunk)
    eval_step = build_eval_step(model)
    x = jax.numpy.zeros((batch, 3, 224, 224), jax.numpy.float32)
    t0 = _time.perf_counter()
    compiled = eval_step.lower(carry[0], carry[2], x).compile()
    compile_s = _time.perf_counter() - t0
    reg.register("bench/resnet50/eval", "train", compiled=compiled,
                 compile_s=compile_s, items_per_call=batch)
    row["programs_resnet50_eval_hbm_bytes"] = int(
        reg.get("bench/resnet50/eval").hbm_bytes)
    if infer_rate:
        prof = reg.record_rate("bench/resnet50/eval", infer_rate)
        if prof is not None and prof.mfu is not None:
            row["programs_resnet50_eval_mfu"] = round(prof.mfu, 4)
    return row


def _bench_elastic():
    """ELASTIC row: what async per-shard checkpointing buys, as
    sentinel-tracked numbers. Leg 1 trains the seeded chaos workload
    with SYNC (gather + inline write) checkpoints and reads the mean
    ``train/checkpoint/save_s`` stall; leg 2 repeats it with the
    ASYNC format-3 writer — the stall shrinks to the snapshot copy
    and the hidden tail lands in ``train/checkpoint/async_write_s``;
    leg 3 times a fresh Optimizer resuming from the committed elastic
    checkpoint to its first completed step (load + cross-layout
    reshard + compile + one step: the number a preempted pod pays
    before training again)."""
    import shutil
    import tempfile
    import time

    import bigdl_tpu.telemetry as telemetry
    from bigdl_tpu.optim import SGD, max_iteration, several_iteration
    from bigdl_tpu.optim.optimizer import Optimizer
    from bigdl_tpu.tools.chaos import _build_workload

    steps = int(os.environ.get("BENCH_ELASTIC_STEPS", 8))
    every = int(os.environ.get("BENCH_ELASTIC_EVERY", 2))
    save_h = telemetry.histogram("train/checkpoint/save_s")
    async_h = telemetry.histogram("train/checkpoint/async_write_s")
    workdir = tempfile.mkdtemp(prefix="bench-elastic-")

    def leg(ckpt, async_write, extra_steps=0):
        model, ds, crit = _build_workload("tiny", 42, 8)
        opt = Optimizer(model, ds, crit, batch_size=8)
        opt.set_optim_method(SGD(learning_rate=0.1, momentum=0.9))
        opt.set_end_when(max_iteration(steps + extra_steps))
        opt.set_checkpoint(ckpt, several_iteration(every),
                           async_write=async_write)
        opt.optimize()

    row = {}
    try:
        c0, s0 = save_h.count(), save_h.sum()
        leg(os.path.join(workdir, "sync"), False)
        c1, s1 = save_h.count(), save_h.sum()
        row["elastic_ckpt_stall_ms_sync"] = round(
            (s1 - s0) / max(1, c1 - c0) * 1000.0, 3)

        a0, t0 = async_h.count(), async_h.sum()
        leg(os.path.join(workdir, "async"), True)
        c2, s2 = save_h.count(), save_h.sum()
        a1, t1 = async_h.count(), async_h.sum()
        row["elastic_ckpt_stall_ms_async"] = round(
            (s2 - s1) / max(1, c2 - c1) * 1000.0, 3)
        row["elastic_ckpt_async_write_ms"] = round(
            (t1 - t0) / max(1, a1 - a0) * 1000.0, 3)

        w0 = time.time()
        leg(os.path.join(workdir, "async"), True, extra_steps=1)
        row["elastic_resume_to_first_step_s"] = round(time.time() - w0,
                                                      3)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return row


def _bench_kernels():
    """KERNELS row: what the pallas kernel layer buys, as
    sentinel-tracked numbers. Leg 1 registers the SAME causal
    attention forward twice in ``telemetry.programs`` — flash kernel
    on (``kernel=pallas``) vs einsum reference (``kernel=reference``)
    — and reports each program's measured rate and MFU, so the gauges
    and the scoreboard agree by construction. Leg 2 runs the same
    seeded generation burst through two fresh GenerationServices,
    ragged decode kernel on vs off, and reports decode tokens/sec both
    ways plus the speedup. (On CPU the on-legs run the pallas
    interpreter, so the CPU numbers document equivalence overhead, not
    a win — the TPU trajectory is the one the sentinel gates.)"""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu import kernels
    from bigdl_tpu.generation import GenerationConfig, GenerationService
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.nn.attention import dot_product_attention
    from bigdl_tpu.telemetry import programs
    from bigdl_tpu.tools.synthetic import seeded_rng
    from bigdl_tpu.utils.random import RandomGenerator

    b = int(os.environ.get("BENCH_KERNELS_BATCH", 4))
    heads = int(os.environ.get("BENCH_KERNELS_HEADS", 8))
    seq = int(os.environ.get("BENCH_KERNELS_SEQ", 512))
    hd = int(os.environ.get("BENCH_KERNELS_HEAD_DIM", 64))
    iters = int(os.environ.get("BENCH_ITERS", 6))
    row = {}
    reg = programs.registry()

    kq, kk, kv = jax.random.split(jax.random.PRNGKey(31), 3)
    q = jax.random.normal(kq, (b, heads, seq, hd), jnp.float32)
    k = jax.random.normal(kk, (b, heads, seq, hd), jnp.float32)
    v = jax.random.normal(kv, (b, heads, seq, hd), jnp.float32)

    def attn_leg(tag, cfg):
        from bigdl_tpu.kernels.dispatch import taken_in_thread

        with kernels.use(cfg):
            fn = jax.jit(lambda q_, k_, v_: dot_product_attention(
                q_, k_, v_, causal=True))
            t0 = time.perf_counter()
            # label by trace EVIDENCE, like every other register site:
            # a declined dispatch (shape over the VMEM budget) must
            # report its leg as reference, not fake a pallas number
            taken_before = taken_in_thread()
            compiled = fn.lower(q, k, v).compile()
            compile_s = time.perf_counter() - t0
            name = f"bench/attention/{tag}"
            reg.register(name, "serving", compiled=compiled,
                         compile_s=compile_s, items_per_call=b * seq,
                         kernel=("pallas"
                                 if taken_in_thread() > taken_before
                                 else "reference"))
            jax.block_until_ready(compiled(q, k, v))  # warm
            t0 = time.perf_counter()
            out = None
            for _ in range(iters):
                out = compiled(q, k, v)
            jax.block_until_ready(out)  # sync once per timed window
            dt = time.perf_counter() - t0
            return reg.record_rate(name, b * seq * iters / dt), dt

    p_on, dt_on = attn_leg("pallas", kernels.KernelConfig.all_on())
    p_off, dt_off = attn_leg("reference", kernels.KernelConfig.off())
    row["kernels_attention_tokens_per_sec_on"] = round(
        b * seq * iters / dt_on, 1)
    row["kernels_attention_tokens_per_sec_off"] = round(
        b * seq * iters / dt_off, 1)
    row["kernels_attention_mfu_on"] = round(p_on.mfu or 0.0, 4) \
        if p_on is not None else 0.0
    row["kernels_attention_mfu_off"] = round(p_off.mfu or 0.0, 4) \
        if p_off is not None else 0.0

    vocab = int(os.environ.get("BENCH_KERNELS_VOCAB", 8192))
    hidden = int(os.environ.get("BENCH_KERNELS_HIDDEN", 512))
    layers = int(os.environ.get("BENCH_KERNELS_LAYERS", 4))
    max_len = int(os.environ.get("BENCH_KERNELS_LEN", 512))
    slots = int(os.environ.get("BENCH_KERNELS_SLOTS", 16))
    n_reqs = int(os.environ.get("BENCH_KERNELS_REQS", 24))
    max_new = int(os.environ.get("BENCH_KERNELS_NEW", 32))

    def decode_leg(cfg) -> float:
        with kernels.use(cfg):
            RandomGenerator.set_seed(13)
            model = TransformerLM(vocab_size=vocab, hidden_size=hidden,
                                  num_layers=layers, num_heads=8,
                                  max_len=max_len).evaluate()
            model.ensure_initialized()
            svc = GenerationService(config=GenerationConfig(
                slots=slots, max_len=max_len,
                prefill_rows=min(4, slots),
                max_queue=max(n_reqs, 256)))
            svc.load("klm", model)  # warmup compiles outside the timing
            r = seeded_rng(14)
            prompts = [r.randint(1, vocab,
                                 r.randint(4, max_len - max_new))
                       .astype(np.int32) for _ in range(n_reqs)]
            t0 = time.time()
            streams = [svc.generate("klm", p, max_new_tokens=max_new)
                       for p in prompts]
            total = sum(len(s.result()) for s in streams)
            dt = time.time() - t0
            svc.shutdown()
            return total / dt

    tps_on = decode_leg(kernels.KernelConfig.all_on())
    tps_off = decode_leg(kernels.KernelConfig.off())
    row["kernels_decode_tokens_per_sec_on"] = round(tps_on, 1)
    row["kernels_decode_tokens_per_sec_off"] = round(tps_off, 1)
    row["kernels_decode_speedup"] = round(tps_on / tps_off, 3)
    return row


def _bench_tuned():
    """TUNED row: what the autotuner's winner buys over the hand-picked
    defaults. Runs ONE prune-then-measure sweep over the bounded smoke
    spaces (``bigdl_tpu.autotune.defaults``) — the default config is a
    point IN those spaces, so winner and baseline come from the same
    seeded windows and the speedup is attributable to configuration,
    not noise. ``BENCH_TUNED_OUT`` additionally saves the tuned.json
    artifact the sweep produced."""
    from bigdl_tpu.autotune import defaults as dflt
    from bigdl_tpu.autotune import save_tuned
    from bigdl_tpu.tools.autotune import run_autotune

    seed = int(os.environ.get("BENCH_TUNED_SEED", 0))
    iters = int(os.environ.get("BENCH_ITERS", 6))
    cfg = run_autotune(("train", "serving"), seed=seed, iters=iters,
                       smoke=True, log=lambda *_a, **_k: None)
    out = os.environ.get("BENCH_TUNED_OUT")
    if out:
        save_tuned(cfg, out)

    def entry_for(regime, want):
        want = {k: (list(v) if isinstance(v, tuple) else v)
                for k, v in want.items()}
        for e in cfg.leaderboard:
            if e.get("ok") and e["regime"] == regime and all(
                    e["config"].get(k) == v for k, v in want.items()):
                return e
        return None

    row = {}
    legs = (("train", "train_steps_per_sec",
             dflt.DEFAULT_TRAIN_CONFIG),
            ("serving", "decode_tokens_per_sec",
             dflt.DEFAULT_SERVING_CONFIG))
    for regime, metric, default_cfg in legs:
        winner = entry_for(regime, cfg.winners.get(regime, {}))
        default = entry_for(regime, default_cfg)
        if winner is None or default is None:
            continue
        row[f"tuned_{metric}"] = round(winner["objective"], 1)
        row[f"default_{metric}"] = round(default["objective"], 1)
        if default["objective"] > 0:
            row[f"tuned_vs_default_{regime}_speedup"] = round(
                winner["objective"] / default["objective"], 3)
    return row


def _bench_transformer_lm():
    """TransformerLM 6L/512d/8H seq 512, batch 16: full train steps
    (fwd+bwd+SGD) under one scanned dispatch; returns tokens/sec.

    ONE implementation serves the scoreboard metric and the ceiling
    ablation (tools/ceiling.framework_tlm) — they must measure the same
    program, so this only parameterizes that harness."""
    from bigdl_tpu.tools import ceiling as C

    C.BATCH = int(os.environ.get("BENCH_LM_BATCH", 16))
    C.SCAN = int(os.environ.get("BENCH_SCAN", 8))
    C.TLM["seq"] = int(os.environ.get("BENCH_LM_SEQ", 512))
    iters = int(os.environ.get("BENCH_ITERS", 6))
    seqs_per_sec = C.framework_tlm(iters)
    return seqs_per_sec * C.TLM["seq"]


if __name__ == "__main__":
    main()
